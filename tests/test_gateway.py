"""Fleet gateway tests: routing law, circuit breaking, stream failover.

The replicas here are real HTTP servers (FakeReplica) speaking the
runtime's NDJSON protocol with a DETERMINISTIC generator — the emitted
text is a pure function of (prompt, seed, temperature), identical on
every replica, exactly the property PR 9's replay machinery guarantees
for greedy/seeded streams. That makes the failover contract directly
checkable: kill replica A mid-stream, let the gateway splice replica B
onto the same client stream, and compare bytes against an uninterrupted
reference run.

The chaos drills (-m chaos) ride the gateway.route / gateway.stream
fault points; drill 9 in CI (kill replica mid-stream under load) runs
TestChaosDrills::test_drill9_replica_killed_mid_stream_under_load.
"""

import hashlib
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ollama_operator_tpu.operator import gateway as gwmod
from ollama_operator_tpu.operator.client import fetch_replica_ps
from ollama_operator_tpu.operator.gateway import Gateway, NoReplicas
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.trace import FLIGHT
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS


# ---------------------------------------------------------------------------
# deterministic fake replica
# ---------------------------------------------------------------------------

def gen_pieces(key: str, n: int):
    """The deterministic 'model': piece i is a pure function of the
    request key and position, so any replica regenerates identical text."""
    out = []
    for i in range(n):
        h = hashlib.sha256(f"{key}|{i}".encode()).hexdigest()
        out.append(f" {h[:4]}")
    return out


def request_key(body):
    """What the generated text depends on — greedy ignores the seed
    (argmax is argmax), seeded sampling depends on it."""
    if "messages" in body:
        prompt = "".join((m.get("content") or "")
                         for m in body.get("messages") or [])
    else:
        prompt = (body.get("system") or "") + (body.get("prompt") or "")
    o = body.get("options") or {}
    t = float(o.get("temperature", 0.7))
    if t == 0.0:
        return f"greedy|{prompt}"
    return f"sampled|{prompt}|seed={o.get('seed')}"


def expected_text(body):
    o = (body or {}).get("options") or {}
    return "".join(gen_pieces(request_key(body),
                              int(o.get("num_predict", 8))))


class FakeReplica:
    """One backend server. Controls: ``ctl['down']`` refuses every
    request at the socket level (replica death), ``ctl['die_after']``
    severs the NEXT generate stream after N data frames and then marks
    the replica down (death mid-stream), ``ctl['draining']`` flips
    /readyz to the drain 503."""

    def __init__(self):
        self.ctl = {"down": False, "die_after": None, "draining": False,
                    "slow_ready_s": 0.0, "stall_after": None}
        self.seen = []          # prompts served (prefix_probe evidence)
        self.served = 0
        self.stall = threading.Event()   # releases wedged streams
        self._lock = threading.Lock()
        replica = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *_a):
                pass

            def _down(self):
                if replica.ctl["down"]:
                    # hard death: close the socket without a response
                    self.close_connection = True
                    self.connection.close()
                    return True
                return False

            def _json(self, obj, status=200):
                data = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self._down():
                    return
                if self.path == "/readyz":
                    if replica.ctl["slow_ready_s"]:
                        import time as _t
                        _t.sleep(replica.ctl["slow_ready_s"])
                    if replica.ctl["draining"]:
                        self._json({"status": "draining"}, 503)
                    else:
                        self._json({"status": "ok"})
                    return
                if self.path == "/api/ps":
                    with replica._lock:
                        active = replica.served
                    self._json({"models": [{
                        "name": "phi", "utilization": {"occupancy": 0.1},
                        "lifecycle": {"state": "serving",
                                      "active_streams": 0},
                        "admission": {"queued_by_class": {}},
                    }]})
                    return
                self._json({"error": "not found"}, 404)

            def do_POST(self):
                if self._down():
                    return
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path == "/api/prefix_probe":
                    prompt = ((body.get("system") or "")
                              + (body.get("prompt") or ""))
                    best = 0
                    with replica._lock:
                        for s in replica.seen:
                            k = 0
                            for a, b in zip(s, prompt):
                                if a != b:
                                    break
                                k += 1
                            best = max(best, k)
                    self._json({"model": body.get("model"),
                                "matched_tokens": best // 4,
                                "prompt_tokens": len(prompt) // 4})
                    return
                if self.path in ("/api/generate", "/api/chat"):
                    self._generate(body)
                    return
                self._json({"ok": True})

            def _chunk(self, data):
                self.wfile.write(f"{len(data):x}\r\n".encode() + data
                                 + b"\r\n")
                self.wfile.flush()

            def _generate(self, body):
                if "messages" in body:
                    prompt = "".join((m.get("content") or "")
                                     for m in body.get("messages") or [])
                else:
                    prompt = ((body.get("system") or "")
                              + (body.get("prompt") or ""))
                o = body.get("options") or {}
                n = int(o.get("num_predict", 8))
                pieces = gen_pieces(request_key(body), n)
                with replica._lock:
                    replica.seen.append(prompt)
                    replica.served += 1
                    die_after = replica.ctl["die_after"]
                    stall_after = replica.ctl["stall_after"]
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                chat = self.path == "/api/chat"
                for i, piece in enumerate(pieces):
                    if stall_after is not None and i >= stall_after:
                        # gateway-crash drills: wedge mid-stream (socket
                        # alive, no bytes) until the test releases us
                        replica.ctl["stall_after"] = None
                        replica.stall.wait(30.0)
                        stall_after = None
                    if die_after is not None and i >= die_after:
                        # replica death mid-stream: no terminal chunk,
                        # socket torn down, and the replica stays dead
                        replica.ctl["die_after"] = None
                        replica.ctl["down"] = True
                        self.close_connection = True
                        self.connection.close()
                        return
                    if chat:
                        frame = {"model": body.get("model"),
                                 "message": {"role": "assistant",
                                             "content": piece},
                                 "done": False}
                    else:
                        frame = {"model": body.get("model"),
                                 "response": piece, "done": False}
                    self._chunk(json.dumps(frame).encode() + b"\n")
                final = {"model": body.get("model"), "done": True,
                         "done_reason": "stop", "eval_count": n}
                if chat:
                    final["message"] = {"role": "assistant", "content": ""}
                else:
                    final["response"] = ""
                self._chunk(json.dumps(final).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def replicas(request):
    """Two fake replicas + teardown; ask for more via indirect param."""
    n = getattr(request, "param", 2)
    reps = [FakeReplica() for _ in range(n)]
    yield reps
    for r in reps:
        r.stop()


@pytest.fixture()
def gw_env(monkeypatch):
    """Deterministic gateway knobs for tests: no background scrape, fast
    circuits, no hedging."""
    monkeypatch.setenv("TPU_GATEWAY_EJECT_FAILURES", "2")
    monkeypatch.setenv("TPU_GATEWAY_EJECT_S", "0.05")
    monkeypatch.setenv("TPU_GATEWAY_SLOW_SCRAPE_MS", "5000")
    monkeypatch.setenv("TPU_GATEWAY_HASH_CHUNK", "64")
    return monkeypatch


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def make_gateway(reps, **kw):
    kw.setdefault("scrape_period_s", 0)
    kw.setdefault("port", 0)
    gw = Gateway(replicas=[(f"rep-{i}", r.url)
                           for i, r in enumerate(reps)], **kw)
    return gw


def stream_frames(base_url, path, body, timeout=30.0):
    """POST and parse the NDJSON response into frames; mid-stream socket
    errors surface as exceptions (the gateway must never let them)."""
    req = urllib.request.Request(
        f"{base_url}{path}", data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        raw = resp.read().decode()
    return [json.loads(line) for line in raw.splitlines() if line.strip()]


def joined_text(frames, chat=False):
    if chat:
        return "".join((f.get("message") or {}).get("content", "")
                       for f in frames if not f.get("done"))
    return "".join(f.get("response", "") for f in frames
                   if not f.get("done") and "error" not in f)


def metric(name, labels=""):
    return METRICS.get(name, labels)


# ---------------------------------------------------------------------------
# routing law
# ---------------------------------------------------------------------------

class TestRouting:
    def test_chunk_hashes_are_chained_and_page_aligned(self, gw_env):
        gw = Gateway(replicas=["http://x"], port=0, scrape_period_s=0)
        a = gw.chunk_hashes("a" * 128)
        b = gw.chunk_hashes("a" * 128 + "b" * 70)
        assert len(a) == 2 and len(b) == 3  # partial tail excluded
        assert b[:2] == a  # shared prefix -> identical chain prefix
        c = gw.chunk_hashes("c" * 64 + "a" * 64)
        assert c[1] != a[1]  # chain commits to EVERYTHING before

    def test_affinity_hit_after_first_route(self, gw_env):
        gw = Gateway(replicas=["http://a", "http://b"], port=0,
                     scrape_period_s=0)
        key = "s" * 200
        name1, path1 = gw.pick(key)
        assert path1 == "least_loaded"
        name2, path2 = gw.pick(key)
        assert (name2, path2) == (name1, "affinity")
        # a longer prompt sharing the prefix still hits the table
        name3, path3 = gw.pick(key + "x" * 80)
        assert (name3, path3) == (name1, "affinity")

    def test_least_loaded_breaks_toward_idle_replica(self, gw_env):
        gw = Gateway(replicas=["http://a", "http://b"], port=0,
                     scrape_period_s=0)
        gw._replicas["replica-0"].load = 5.0
        name, path = gw.pick("z" * 100)
        assert (name, path) == ("replica-1", "least_loaded")

    def test_probe_scatter_finds_warm_replica(self, gw_env, replicas):
        a, b = replicas
        b.seen.append("s" * 300)  # replica B already served this prefix
        gw = make_gateway(replicas)
        name, path = gw.pick("s" * 300,
                             probe_body={"model": "phi", "prompt": "s" * 300})
        assert (name, path) == ("rep-1", "probe")

    def test_probe_disabled_by_knob(self, gw_env, replicas):
        gw_env.setenv("TPU_GATEWAY_PROBE", "0")
        a, b = replicas
        b.seen.append("s" * 300)
        gw = make_gateway(replicas)
        _, path = gw.pick("s" * 300,
                          probe_body={"model": "phi", "prompt": "s" * 300})
        assert path == "least_loaded"

    def test_no_replicas_raises_with_finite_retry(self, gw_env):
        gw = Gateway(replicas=[], port=0, scrape_period_s=0)
        with pytest.raises(NoReplicas) as ei:
            gw.pick("x" * 100)
        assert 1 <= ei.value.retry_after_s <= 30


# ---------------------------------------------------------------------------
# health state machine / circuit breaking
# ---------------------------------------------------------------------------

class TestCircuit:
    def test_scrape_heals_probe_to_healthy(self, gw_env, replicas):
        gw = make_gateway(replicas)
        assert gw.state_counts()["probe"] == 2
        gw.scrape_once()
        assert gw.state_counts()["healthy"] == 2

    def test_dead_replica_ejects_after_consecutive_failures(self, gw_env,
                                                            replicas):
        a, b = replicas
        a.ctl["down"] = True
        gw = make_gateway(replicas)
        before = metric("tpu_model_gateway_ejections_total",
                        '{cause="not_ready"}')
        gw.scrape_once()
        gw.scrape_once()  # EJECT_FAILURES=2
        counts = gw.state_counts()
        assert counts["ejected"] == 1 and counts["healthy"] == 1
        assert metric("tpu_model_gateway_ejections_total",
                      '{cause="not_ready"}') == before + 1
        # routing never lands on the open circuit
        for i in range(6):
            name, _ = gw.pick(f"q{i}" * 60)
            assert name == "rep-1"

    def test_draining_replica_is_parked_not_ejected(self, gw_env, replicas):
        a, b = replicas
        gw = make_gateway(replicas)
        gw.scrape_once()
        a.ctl["draining"] = True
        before = metric("tpu_model_gateway_ejections_total",
                        '{cause="not_ready"}')
        gw.scrape_once()
        counts = gw.state_counts()
        assert counts["draining"] == 1
        assert metric("tpu_model_gateway_ejections_total",
                      '{cause="not_ready"}') == before
        # drain ends -> replica returns without ever opening the circuit
        a.ctl["draining"] = False
        gw.scrape_once()
        assert gw.state_counts()["healthy"] == 2

    def test_half_open_admits_exactly_one_probe_request(self, gw_env):
        import time
        gw = Gateway(replicas=["http://a"], port=0, scrape_period_s=0)
        r = gw._replicas["replica-0"]
        with gw._lock:
            gw._fail_locked(r, "failures", "boom")
            gw._fail_locked(r, "failures", "boom")
        assert r.state == "ejected"
        with pytest.raises(NoReplicas):
            gw.pick("x" * 100)  # circuit open: nothing routable
        time.sleep(0.06)  # EJECT_S=0.05
        name, _ = gw.pick("x" * 100)  # half-open: the ONE trial
        assert name == "replica-0" and r.state == "half_open"
        with pytest.raises(NoReplicas):
            gw.pick("y" * 100)  # second request denied while trial runs
        ok_before = metric("tpu_model_gateway_half_open_probes_total",
                           '{result="ok"}')
        gw._request_ok("replica-0")
        assert r.state == "healthy"
        assert metric("tpu_model_gateway_half_open_probes_total",
                      '{result="ok"}') == ok_before + 1

    def test_half_open_failure_reopens_circuit(self, gw_env):
        import time
        gw = Gateway(replicas=["http://a"], port=0, scrape_period_s=0)
        r = gw._replicas["replica-0"]
        with gw._lock:
            gw._fail_locked(r, "failures", "boom")
            gw._fail_locked(r, "failures", "boom")
        time.sleep(0.06)
        gw.pick("x" * 100)
        fail_before = metric("tpu_model_gateway_half_open_probes_total",
                             '{result="fail"}')
        gw._request_failed("replica-0", "still broken")
        assert r.state == "ejected"
        assert metric("tpu_model_gateway_half_open_probes_total",
                      '{result="fail"}') == fail_before + 1

    def test_slow_scrape_counts_as_failure(self, gw_env, replicas):
        gw_env.setenv("TPU_GATEWAY_SLOW_SCRAPE_MS", "10")
        a, b = replicas
        a.ctl["slow_ready_s"] = 0.05
        gw = make_gateway(replicas)
        before = metric("tpu_model_gateway_ejections_total",
                        '{cause="slow"}')
        gw.scrape_once()
        gw.scrape_once()
        assert gw.state_counts()["ejected"] == 1
        assert metric("tpu_model_gateway_ejections_total",
                      '{cause="slow"}') == before + 1


# ---------------------------------------------------------------------------
# stream failover (the zero-error contract)
# ---------------------------------------------------------------------------

@pytest.fixture()
def served_gw(gw_env, replicas):
    gw = make_gateway(replicas).start()
    yield gw, replicas
    gw.stop()


GREEDY = {"temperature": 0, "num_predict": 10}
SEEDED = {"temperature": 0.9, "seed": 42, "num_predict": 10}
SAMPLED = {"temperature": 0.9, "num_predict": 10}


class TestFailover:
    def _reference(self, body):
        return expected_text(body)

    def test_greedy_stream_continues_bit_identically(self, served_gw):
        gw, (a, b) = served_gw
        body = {"model": "phi", "prompt": "p" * 200, "options": dict(GREEDY),
                "stream": True}
        a.ctl["die_after"] = 4  # least-loaded tiebreak routes to rep-0
        before = metric("tpu_model_gateway_failovers_total",
                        '{result="replayed"}')
        frames = stream_frames(gw.base_url, "/api/generate", body)
        assert not any("error" in f for f in frames)
        assert frames[-1].get("done") is True
        assert joined_text(frames) == self._reference(body)
        assert metric("tpu_model_gateway_failovers_total",
                      '{result="replayed"}') == before + 1
        assert gw.journal_stats()["live"] == 0

    def test_seeded_stream_continues_bit_identically(self, served_gw):
        gw, (a, b) = served_gw
        body = {"model": "phi", "prompt": "q" * 200, "options": dict(SEEDED),
                "stream": True}
        a.ctl["die_after"] = 3
        frames = stream_frames(gw.base_url, "/api/generate", body)
        assert not any("error" in f for f in frames)
        assert joined_text(frames) == self._reference(body)

    def test_chat_stream_failover(self, served_gw):
        gw, (a, b) = served_gw
        body = {"model": "phi",
                "messages": [{"role": "user", "content": "m" * 200}],
                "options": dict(GREEDY), "stream": True}
        a.ctl["die_after"] = 4
        frames = stream_frames(gw.base_url, "/api/chat", body)
        assert not any("error" in f for f in frames)
        assert joined_text(frames, chat=True) == self._reference(body)

    def test_non_replayable_stream_errors_exactly_once(self, served_gw):
        gw, (a, b) = served_gw
        body = {"model": "phi", "prompt": "r" * 200,
                "options": dict(SAMPLED), "stream": True}
        a.ctl["die_after"] = 4
        before = metric("tpu_model_gateway_failovers_total",
                        '{result="errored"}')
        frames = stream_frames(gw.base_url, "/api/generate", body)
        errors = [f for f in frames if "error" in f]
        assert len(errors) == 1  # the classic exactly-once contract
        assert frames[-1] is errors[0]  # terminal, nothing after it
        retry = errors[0].get("retry_after_s")
        assert retry is not None and 1 <= retry <= 30
        assert metric("tpu_model_gateway_failovers_total",
                      '{result="errored"}') == before + 1
        assert gw.journal_stats()["live"] == 0

    def test_unstarted_request_fails_over_unconditionally(self, served_gw):
        gw, (a, b) = served_gw
        a.ctl["down"] = True  # dead before a single frame
        body = {"model": "phi", "prompt": "u" * 200,
                "options": dict(SAMPLED), "stream": True}
        before = metric("tpu_model_gateway_failovers_total",
                        '{result="requeued"}')
        frames = stream_frames(gw.base_url, "/api/generate", body)
        assert not any("error" in f for f in frames)
        assert joined_text(frames) == self._reference(body)
        assert metric("tpu_model_gateway_failovers_total",
                      '{result="requeued"}') >= before + 1

    def test_non_streaming_client_survives_failover(self, served_gw):
        gw, (a, b) = served_gw
        a.ctl["die_after"] = 4
        body = {"model": "phi", "prompt": "n" * 200,
                "options": dict(GREEDY), "stream": False}
        req = urllib.request.Request(
            f"{gw.base_url}/api/generate", data=json.dumps(body).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30.0) as resp:
            out = json.loads(resp.read().decode())
        assert out.get("done") is True
        assert out["response"] == self._reference(body)

    def test_all_replicas_down_is_503_with_retry_after(self, served_gw):
        gw, (a, b) = served_gw
        a.ctl["down"] = True
        b.ctl["down"] = True
        body = {"model": "phi", "prompt": "d" * 100,
                "options": dict(GREEDY), "stream": True}
        with pytest.raises(urllib.error.HTTPError) as ei:
            stream_frames(gw.base_url, "/api/generate", body)
        assert ei.value.code == 503
        assert int(ei.value.headers.get("Retry-After") or 0) >= 1


# ---------------------------------------------------------------------------
# journal / endpoints
# ---------------------------------------------------------------------------

class TestJournalAndEndpoints:
    def test_journal_ring_is_bounded(self, gw_env, replicas):
        gw_env.setenv("TPU_GATEWAY_JOURNAL", "3")
        gw = make_gateway(replicas).start()
        try:
            for i in range(6):
                body = {"model": "phi", "prompt": f"j{i}" * 60,
                        "options": dict(GREEDY), "stream": True}
                stream_frames(gw.base_url, "/api/generate", body)
            stats = gw.journal_stats()
            assert stats == {"live": 0, "kept": 3}
        finally:
            gw.stop()

    def test_journal_entry_records_identity_and_hash(self, gw_env, replicas):
        gw = make_gateway(replicas).start()
        try:
            body = {"model": "phi", "prompt": "h" * 120,
                    "options": {"temperature": 0, "num_predict": 6,
                                "priority": "interactive",
                                "tenant": "acme"},
                    "stream": True}
            stream_frames(gw.base_url, "/api/generate", body)
            entry = next(iter(gw._done.values()))
            assert entry["class"] == "interactive"
            assert entry["tenant"] == "acme"
            assert entry["replayable"] is True
            want = hashlib.sha256(
                expected_text(body).encode()).hexdigest()
            assert entry["hash"] == want
        finally:
            gw.stop()

    def test_status_and_readyz_and_aggregate_ps(self, gw_env, replicas):
        gw = make_gateway(replicas).start()
        try:
            gw.scrape_once()
            st = json.loads(urllib.request.urlopen(
                f"{gw.base_url}/gateway/status", timeout=5).read())
            assert len(st["replicas"]) == 2
            assert all(r["state"] == "healthy" for r in st["replicas"])
            rz = urllib.request.urlopen(f"{gw.base_url}/readyz", timeout=5)
            assert rz.status == 200
            ps = json.loads(urllib.request.urlopen(
                f"{gw.base_url}/api/ps", timeout=5).read())
            assert {m["replica"] for m in ps["models"]} == {"rep-0", "rep-1"}
        finally:
            gw.stop()

    def test_readyz_503_when_fleet_unroutable(self, gw_env, replicas):
        for r in replicas:
            r.ctl["down"] = True
        gw = make_gateway(replicas)
        rep = gw._replicas["rep-0"]
        rep2 = gw._replicas["rep-1"]
        with gw._lock:
            for rr in (rep, rep2):
                gw._fail_locked(rr, "failures", "x")
                gw._fail_locked(rr, "failures", "x")
        gw.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{gw.base_url}/readyz", timeout=5)
            assert ei.value.code == 503
        finally:
            gw.stop()


# ---------------------------------------------------------------------------
# operator scrape-failure accounting (satellite 3)
# ---------------------------------------------------------------------------

class TestScrapeFailureAccounting:
    def test_network_failure_counts_and_leaves_breadcrumb(self):
        before = metric("tpu_model_scrape_failures_total",
                        '{cause="network"}')
        seq = FLIGHT.seq
        out = fetch_replica_ps("http://127.0.0.1:9/api/ps", timeout=0.2)
        assert out is None
        assert metric("tpu_model_scrape_failures_total",
                      '{cause="network"}') == before + 1
        evs = [e for e in FLIGHT.snapshot()
               if e["seq"] > seq and e["kind"] == "scrape_failed"]
        assert evs and evs[-1]["cause"] == "network"

    def test_injected_fault_counts_as_fault(self):
        FAULTS.arm("operator.scrape", "fail:once")
        before = metric("tpu_model_scrape_failures_total",
                        '{cause="fault"}')
        assert fetch_replica_ps("http://127.0.0.1:9/api/ps") is None
        assert metric("tpu_model_scrape_failures_total",
                      '{cause="fault"}') == before + 1

    def test_http_error_counts_as_http(self, replicas):
        a, _ = replicas
        before = metric("tpu_model_scrape_failures_total",
                        '{cause="http"}')
        assert fetch_replica_ps(f"{a.url}/nope", timeout=2.0) is None
        assert metric("tpu_model_scrape_failures_total",
                      '{cause="http"}') == before + 1


# ---------------------------------------------------------------------------
# K=4 fake-kube fleet e2e (CI gateway-smoke drives this)
# ---------------------------------------------------------------------------

SYSTEM_512_TOK = ("You are a meticulous TPU serving assistant. " * 48)[:2048]


@pytest.mark.parametrize("replicas", [4], indirect=True)
class TestFleetE2E:
    def test_k4_shared_prefix_fleet_and_replica_kill(self, gw_env, replicas,
                                                     tmp_path):
        """The ISSUE acceptance arm: K=4 fleet, every request sharing a
        512-token system prompt. Cache-aware routing must concentrate the
        shared prefix (affinity hits ~ a single-replica fleet would get)
        and a replica kill mid-run must stay invisible to greedy
        clients. Publishes the per-replica table when GATEWAY_TABLE is
        set (the CI job summary)."""
        import os
        gw = make_gateway(replicas).start()
        routes_before = {p: metric("tpu_model_gateway_routes_total",
                                   f'{{path="{p}"}}')
                         for p in ("affinity", "probe", "least_loaded")}
        fo_before = {r: metric("tpu_model_gateway_failovers_total",
                               f'{{result="{r}"}}')
                     for r in ("replayed", "requeued", "errored")}
        try:
            texts = {}
            for i in range(12):
                body = {"model": "phi", "system": SYSTEM_512_TOK,
                        "prompt": f"question {i}: what is step {i}?",
                        "options": dict(GREEDY), "stream": True}
                if i == 6:
                    # kill whichever replica owns the hot prefix,
                    # mid-stream
                    hot = max(gw._replicas.values(), key=lambda r: r.served)
                    idx = int(hot.name.split("-")[1])
                    replicas[idx].ctl["die_after"] = 3
                frames = stream_frames(gw.base_url, "/api/generate", body)
                assert not any("error" in f for f in frames), \
                    f"request {i} saw an error frame"
                texts[i] = (joined_text(frames), expected_text(body))
            for i, (got, want) in texts.items():
                assert got == want, f"request {i} diverged"
            routes = {p: metric("tpu_model_gateway_routes_total",
                                f'{{path="{p}"}}') - routes_before[p]
                      for p in routes_before}
            failovers = {r: metric("tpu_model_gateway_failovers_total",
                                   f'{{result="{r}"}}') - fo_before[r]
                         for r in fo_before}
            total = sum(routes.values())
            # a single replica would hit its own cache on every request
            # after the first; the fleet must keep >= 0.9 of that
            # (affinity + probe are both cache hits; the kill forces a
            # handful of cold re-routes)
            single_rate = (12 - 1) / 12
            fleet_rate = (routes["affinity"] + routes["probe"]) / total
            assert fleet_rate >= 0.9 * single_rate, \
                f"fleet hit rate {fleet_rate:.2f} < 0.9x single " \
                f"{single_rate:.2f} (routes={routes})"
            assert failovers["replayed"] >= 1
            assert failovers["errored"] == 0
            assert gw.journal_stats()["live"] == 0
            table_path = os.environ.get("GATEWAY_TABLE")
            if table_path:
                st = gw.status()
                lines = ["| replica | state | served | failed |",
                         "|---|---|---|---|"]
                for r in st["replicas"]:
                    lines.append(f"| {r['name']} | {r['state']} | "
                                 f"{r['served']} | {r['failed']} |")
                lines.append("")
                lines.append(f"routes: {routes}  failovers: {failovers}  "
                             f"fleet_hit_rate: {fleet_rate:.3f} "
                             f"(single-replica {single_rate:.3f})")
                with open(table_path, "a") as f:
                    f.write("\n".join(lines) + "\n")
        finally:
            gw.stop()


# ---------------------------------------------------------------------------
# chaos drills (gateway.route / gateway.stream fault points)
# ---------------------------------------------------------------------------

class TestChaosDrills:
    @pytest.mark.chaos
    def test_route_fault_requeues_unstarted_request(self, served_gw):
        gw, (a, b) = served_gw
        FAULTS.arm("gateway.route", "fail:once")
        before = metric("tpu_model_gateway_failovers_total",
                        '{result="requeued"}')
        body = {"model": "phi", "prompt": "c" * 150,
                "options": dict(GREEDY), "stream": True}
        frames = stream_frames(gw.base_url, "/api/generate", body)
        assert not any("error" in f for f in frames)
        assert joined_text(frames) == expected_text(body)
        assert FAULTS.hits("gateway.route") >= 1

    @pytest.mark.chaos
    def test_stream_fault_persistent_yields_exactly_once_error(self,
                                                               served_gw):
        """A fault that keeps severing EVERY upstream stream exhausts the
        failover budget; the client must still get exactly one terminal
        error frame — never a broken socket."""
        gw, _ = served_gw
        FAULTS.arm("gateway.stream", "fail:after=3")
        body = {"model": "phi", "prompt": "e" * 150,
                "options": dict(GREEDY), "stream": True}
        frames = stream_frames(gw.base_url, "/api/generate", body)
        errors = [f for f in frames if "error" in f]
        assert len(errors) == 1 and frames[-1] is errors[0]

    @pytest.mark.chaos
    @pytest.mark.parametrize("replicas", [4], indirect=True)
    def test_drill9_replica_killed_mid_stream_under_load(self, gw_env,
                                                         replicas):
        """CI chaos-smoke drill 9: kill a replica mid-stream while
        concurrent greedy streams are in flight — zero client-visible
        error frames, the failover counter increments, and the journal
        drains."""
        gw = make_gateway(replicas).start()
        fo_before = metric("tpu_model_gateway_failovers_total",
                           '{result="replayed"}')
        try:
            results = {}
            errors = {}

            def run(i):
                body = {"model": "phi", "system": SYSTEM_512_TOK,
                        "prompt": f"load {i}", "options": dict(GREEDY),
                        "stream": True}
                try:
                    frames = stream_frames(gw.base_url, "/api/generate",
                                           body)
                    results[i] = (frames, expected_text(body))
                except Exception as e:  # noqa: BLE001 — collected below
                    errors[i] = e

            # warm the affinity table so the load concentrates
            run(-1)
            hot = max(gw._replicas.values(), key=lambda r: r.served)
            idx = int(hot.name.split("-")[1])
            replicas[idx].ctl["die_after"] = 2
            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errors, f"client-visible failures: {errors}"
            for i, (frames, want) in results.items():
                assert not any("error" in f for f in frames), \
                    f"stream {i} saw an error frame"
                assert joined_text(frames) == want, f"stream {i} diverged"
            assert metric("tpu_model_gateway_failovers_total",
                          '{result="replayed"}') >= fo_before + 1
            assert gw.journal_stats()["live"] == 0
        finally:
            gw.stop()


# ---------------------------------------------------------------------------
# operator wiring
# ---------------------------------------------------------------------------

class TestOperatorWiring:
    def _model(self, **spec):
        spec.setdefault("image", "phi")
        spec.setdefault("runtime", "cpu")
        return {"apiVersion": "ollama.ayaka.io/v1", "kind": "Model",
                "metadata": {"name": "phi", "namespace": "default",
                             "uid": "u1"},
                "spec": spec}

    def test_gateway_enabled_gating(self):
        from ollama_operator_tpu.operator.types import ModelSpecView
        from ollama_operator_tpu.operator.workload import gateway_enabled
        assert not gateway_enabled(ModelSpecView(self._model()))
        assert gateway_enabled(ModelSpecView(self._model(replicas=3)))
        assert gateway_enabled(ModelSpecView(
            self._model(autoscale={"enabled": True})))
        assert gateway_enabled(ModelSpecView(self._model(gateway=True)))
        assert not gateway_enabled(ModelSpecView(
            self._model(replicas=3, gateway=False)))

    def test_service_selector_points_at_gateway_when_enabled(self):
        from ollama_operator_tpu.operator import workload
        svc = workload.build_model_service(self._model(replicas=3))
        assert svc["spec"]["selector"] == {"app": "ollama-model-phi-gateway"}
        svc1 = workload.build_model_service(self._model())
        assert svc1["spec"]["selector"] == {"app": "ollama-model-phi"}

    def test_gateway_deployment_shape(self):
        from ollama_operator_tpu.operator import workload
        dep = workload.build_gateway_deployment(self._model(replicas=2),
                                                "runtime:test")
        assert dep["metadata"]["name"] == "ollama-model-phi-gateway"
        c = dep["spec"]["template"]["spec"]["containers"][0]
        assert c["command"][-1] == "ollama_operator_tpu.operator.gateway"
        env = {e["name"]: e["value"] for e in c["env"]}
        assert env["TPU_GATEWAY_SELECTOR"] == "default/ollama-model-phi"
        assert "resources" not in c  # no TPU for the gateway

    def test_kube_discovery_lists_ready_pods(self):
        import sys
        sys.path.insert(0, "tests")
        from fake_kube import FakeKube
        kube = FakeKube()
        for i, ip in enumerate(["10.0.0.5", "10.0.0.6"]):
            kube.create({"apiVersion": "v1", "kind": "Pod",
                         "metadata": {"name": f"pod-{i}",
                                      "namespace": "default",
                                      "labels": {"app": "ollama-model-phi"}},
                         "spec": {}})
            kube.set_status("v1", "Pod", "default", f"pod-{i}",
                            {"podIP": ip})
        disc = gwmod.kube_discovery(kube, "default", "ollama-model-phi",
                                    port=11434)
        assert disc() == [("pod-0", "http://10.0.0.5:11434", ""),
                          ("pod-1", "http://10.0.0.6:11434", "")]

    def test_reconciler_creates_gateway_and_repoints_service(self):
        import sys
        sys.path.insert(0, "tests")
        from test_operator_reconciler import (RecordingRecorder, drive,
                                              make_model)
        from fake_kube import FakeKube
        from ollama_operator_tpu.operator.reconciler import ModelReconciler
        kube = FakeKube()
        rec = RecordingRecorder()
        r = ModelReconciler(kube, rec, server_image="runtime:test")
        make_model(kube, replicas=2)
        drive(r, kube)
        gw_dep = kube.get("apps/v1", "Deployment", "default",
                          "ollama-model-phi-gateway")
        assert gw_dep is not None
        assert ("Normal", "GatewayCreated") in rec.events
        svc = kube.get("v1", "Service", "default", "ollama-model-phi")
        assert svc["spec"]["selector"] == {"app": "ollama-model-phi-gateway"}
        # disable the gateway -> deployment removed, selector repointed
        m = kube.get("ollama.ayaka.io/v1", "Model", "default", "phi")
        m["spec"]["gateway"] = False
        kube.update(m)
        drive(r, kube)
        assert kube.get("apps/v1", "Deployment", "default",
                        "ollama-model-phi-gateway") is None
        svc = kube.get("v1", "Service", "default", "ollama-model-phi")
        assert svc["spec"]["selector"] == {"app": "ollama-model-phi"}
        assert ("Normal", "GatewayRemoved") in rec.events
        assert ("Normal", "ServiceSelectorSynced") in rec.events


# ---------------------------------------------------------------------------
# crash recovery: persisted journal + restart resume (tentpole)
# ---------------------------------------------------------------------------

@pytest.fixture()
def persist_env(gw_env, tmp_path):
    """gw_env plus a persisted journal: every gateway built under this
    fixture boots from (and appends to) the same append-log, which is
    exactly the crashed-pod-replacement topology."""
    path = tmp_path / "gateway-journal.ndjson"
    gw_env.setenv("TPU_GATEWAY_PERSIST", str(path))
    gw_env.setenv("TPU_GATEWAY_PERSIST_FLUSH_MS", "5")
    return path


def stream_prefix(base_url, path, body, timeout=2.0):
    """POST and read until the stream wedges (the gateway is about to be
    crashed mid-stream); returns the text the client saw so far."""
    req = urllib.request.Request(
        f"{base_url}{path}", data=json.dumps(body).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    raw = b""
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            while True:
                d = resp.read(1)
                if not d:
                    break
                raw += d
    except (TimeoutError, OSError):
        pass
    text = ""
    for line in raw.decode(errors="replace").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            frame = json.loads(line)
        except ValueError:
            continue  # torn tail: the crash landed mid-frame
        if not frame.get("done") and "error" not in frame:
            text += frame.get("response", "")
    return text


class TestCrashRecovery:
    def _crash_mid_stream(self, replicas, body, stall_after=4):
        """Boot a gateway, wedge the stream after ``stall_after`` frames,
        capture the client-visible prefix, then crash the gateway (stop
        without closing live journal entries). Returns the prefix."""
        for r in replicas:
            r.ctl["stall_after"] = stall_after
        gw1 = make_gateway(replicas).start()
        try:
            prefix = stream_prefix(gw1.base_url, "/api/generate", body)
        finally:
            gw1.stop()  # the crash: live entries stay open in the log
        for r in replicas:
            r.ctl["stall_after"] = None
            r.stall.set()
        return prefix

    def test_restart_resumes_stream_byte_identically(self, persist_env,
                                                     replicas):
        body = {"model": "phi", "prompt": "cr" * 100,
                "options": dict(GREEDY), "stream": True,
                "request_id": "rid-restart-1"}
        restored_before = metric("tpu_model_gateway_persist_restores_total")
        replayed_before = metric("tpu_model_gateway_failovers_total",
                                 '{result="replayed"}')
        prefix = self._crash_mid_stream(replicas, body)
        want = expected_text(body)
        assert 0 < len(prefix) < len(want), "crash must land mid-stream"
        gw2 = make_gateway(replicas).start()
        try:
            assert metric("tpu_model_gateway_persist_restores_total") \
                >= restored_before + 1
            frames = stream_frames(gw2.base_url, "/api/generate", body)
            assert not any("error" in f for f in frames)
            assert frames[-1].get("done") is True
            # the reconnect got exactly the remainder: prefix + resume
            # is byte-identical to an uninterrupted run
            assert prefix + joined_text(frames) == want
            assert metric("tpu_model_gateway_failovers_total",
                          '{result="replayed"}') >= replayed_before + 1
            assert gw2.journal_stats()["live"] == 0
        finally:
            gw2.stop()

    def test_non_replayable_restored_stream_errors_exactly_once(
            self, persist_env, replicas):
        body = {"model": "phi", "prompt": "nr" * 100,
                "options": dict(SAMPLED), "stream": True,
                "request_id": "rid-restart-2"}
        errored_before = metric("tpu_model_gateway_failovers_total",
                                '{result="errored"}')
        prefix = self._crash_mid_stream(replicas, body, stall_after=3)
        assert prefix  # chars were emitted, so a silent regen would fork
        gw2 = make_gateway(replicas).start()
        try:
            frames = stream_frames(gw2.base_url, "/api/generate", body)
            errors = [f for f in frames if "error" in f]
            assert len(errors) == 1 and frames[-1] is errors[0]
            assert int(errors[0].get("retry_after_s", 0)) >= 1
            assert metric("tpu_model_gateway_failovers_total",
                          '{result="errored"}') >= errored_before + 1
        finally:
            gw2.stop()

    def test_compaction_snapshot_restores_affinity(self, persist_env,
                                                   replicas):
        prompt = "af" * 120
        body = {"model": "phi", "prompt": prompt, "options": dict(GREEDY),
                "stream": True}
        gw1 = make_gateway(replicas).start()
        try:
            stream_frames(gw1.base_url, "/api/generate", body)
            owner = max(gw1._replicas.values(), key=lambda r: r.served).name
            # affinity records reach disk via compaction; force one
            gw1._persist.maybe_compact(gw1._snapshot_records, threshold=1)
        finally:
            gw1.stop()
        gw2 = make_gateway(replicas)
        name, path = gw2.pick(prompt)
        assert (name, path) == (owner, "affinity")

    def test_stop_flushes_but_keeps_live_entries_open(self, persist_env,
                                                      replicas):
        body = {"model": "phi", "prompt": "fl" * 100,
                "options": dict(GREEDY), "stream": True,
                "request_id": "rid-flush"}
        self._crash_mid_stream(replicas, body)
        recs = [json.loads(line) for line in
                persist_env.read_text().splitlines() if line.strip()]
        opens = [r for r in recs if r.get("t") == "open"]
        closes = [r for r in recs if r.get("t") == "close"]
        assert opens and not closes  # crashed, not completed


# ---------------------------------------------------------------------------
# drain + remediation-aware Retry-After (tentpole + satellite 1)
# ---------------------------------------------------------------------------

class TestDrainAndRetryAfter:
    def test_begin_drain_sheds_with_finite_retry_after(self, persist_env,
                                                       replicas):
        drain_before = metric("tpu_model_gateway_drain_total")
        gw = make_gateway(replicas).start()
        try:
            gw.begin_drain(timeout_s=0.2)
            assert metric("tpu_model_gateway_drain_total") \
                >= drain_before + 1
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{gw.base_url}/readyz", timeout=5)
            assert ei.value.code == 503
            req = urllib.request.Request(
                f"{gw.base_url}/api/generate",
                data=json.dumps({"model": "phi", "prompt": "x",
                                 "options": dict(GREEDY)}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 503
            assert int(ei.value.headers.get("Retry-After", "0")) >= 1
            assert persist_env.exists()  # drain flushed the journal
        finally:
            gw.stop()

    def test_retry_after_tracks_soonest_ejection_expiry(self, gw_env,
                                                        replicas):
        """Satellite 1: when every replica is mid-remediation the 503's
        Retry-After is computed from the shortest remaining ejection
        timer, not a flat guess."""
        gw_env.setenv("TPU_GATEWAY_EJECT_S", "7")
        for r in replicas:
            r.ctl["down"] = True
        gw = make_gateway(replicas)
        with gw._lock:
            for name in ("rep-0", "rep-1"):
                rr = gw._replicas[name]
                gw._fail_locked(rr, "failures", "x")
                gw._fail_locked(rr, "failures", "x")
        gw.start()
        try:
            req = urllib.request.Request(
                f"{gw.base_url}/api/generate",
                data=json.dumps({"model": "phi", "prompt": "y" * 80,
                                 "options": dict(GREEDY)}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 503
            assert 6 <= int(ei.value.headers["Retry-After"]) <= 8
        finally:
            gw.stop()

    def test_watchdog_ejects_wedged_replica_and_stream_fails_over(
            self, gw_env, replicas):
        """Satellite 3: a replica that wedges mid-stream trips the hedge
        watchdog (stream fails over byte-identically within the bound)
        and its slow scrapes get it ejected."""
        gw_env.setenv("TPU_GATEWAY_HEDGE_MS", "800")
        gw_env.setenv("TPU_GATEWAY_SLOW_SCRAPE_MS", "100")
        gw_env.setenv("TPU_GATEWAY_EJECT_S", "60")
        a, b = replicas
        a.ctl["stall_after"] = 3
        body = {"model": "phi", "prompt": "wd" * 100,
                "options": dict(GREEDY), "stream": True}
        replayed_before = metric("tpu_model_gateway_failovers_total",
                                 '{result="replayed"}')
        gw = make_gateway(replicas).start()
        try:
            t0 = time.monotonic()
            frames = stream_frames(gw.base_url, "/api/generate", body,
                                   timeout=30)
            elapsed = time.monotonic() - t0
            assert not any("error" in f for f in frames)
            assert joined_text(frames) == expected_text(body)
            assert metric("tpu_model_gateway_failovers_total",
                          '{result="replayed"}') >= replayed_before + 1
            assert elapsed < 15, f"watchdog bound blown: {elapsed:.1f}s"
            a.stall.set()
            # now the wedged replica also answers its health scrape
            # slowly: two slow passes cross the ejection threshold
            a.ctl["slow_ready_s"] = 0.4
            gw.scrape_once()
            gw.scrape_once()
            st = json.loads(urllib.request.urlopen(
                f"{gw.base_url}/gateway/status", timeout=5).read())
            states = {r["name"]: r["state"] for r in st["replicas"]}
            assert states["rep-0"] == "ejected"
        finally:
            a.stall.set()
            gw.stop()
