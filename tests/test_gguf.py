"""GGUF container + dequantisation tests.

The k-quant vectorised kernels are checked against straight scalar
transliterations of the ggml per-block loops (independent implementation of
the same layout), and the legacy formats against quantise→dequantise round
trips.
"""

import numpy as np
import pytest

from ollama_operator_tpu.gguf import dequant as DQ
from ollama_operator_tpu.gguf import reader as R
from ollama_operator_tpu.gguf import writer as W

rng = np.random.default_rng(0)


# ---------------------------------------------------------------------------
# scalar references (per-block loops, mirroring ggml's dequantize_row_*)
# ---------------------------------------------------------------------------

def ref_q2_k(raw):
    out = []
    for blk in raw.reshape(-1, 84):
        scales = blk[:16]
        qs = blk[16:80]
        d = np.frombuffer(blk[80:82].tobytes(), np.float16)[0].astype(np.float32)
        dmin = np.frombuffer(blk[82:84].tobytes(), np.float16)[0].astype(np.float32)
        y = np.zeros(256, np.float32)
        i = 0
        is_ = 0
        for n in (0, 128):
            q = qs[n // 4: n // 4 + 32]
            for shift in (0, 2, 4, 6):
                for half in range(2):
                    sc = scales[is_]; is_ += 1
                    for l in range(16):
                        qv = (q[half * 16 + l] >> shift) & 3
                        y[i] = d * (sc & 0xF) * qv - dmin * (sc >> 4)
                        i += 1
        out.append(y)
    return np.concatenate(out)


def ref_q3_k(raw):
    out = []
    for blk in raw.reshape(-1, 110):
        hmask = blk[:32]
        qs = blk[32:96]
        sb = blk[96:108]
        d = np.frombuffer(blk[108:110].tobytes(), np.float16)[0].astype(np.float32)
        aux = np.frombuffer(sb.tobytes(), np.uint32).copy()
        k1, k2 = 0x03030303, 0x0F0F0F0F
        tmp = int(aux[2])
        a = np.zeros(4, np.uint32)
        a[0] = (int(aux[0]) & k2) | (((tmp >> 0) & k1) << 4)
        a[1] = (int(aux[1]) & k2) | (((tmp >> 2) & k1) << 4)
        a[2] = ((int(aux[0]) >> 4) & k2) | (((tmp >> 4) & k1) << 4)
        a[3] = ((int(aux[1]) >> 4) & k2) | (((tmp >> 6) & k1) << 4)
        scales = a.view(np.int8).astype(np.int32) - 32
        y = np.zeros(256, np.float32)
        i = 0
        is_ = 0
        m = 1
        for n in (0, 128):
            q = qs[n // 4: n // 4 + 32]
            for shift in (0, 2, 4, 6):
                for half in range(2):
                    sc = scales[is_]; is_ += 1
                    for l in range(16):
                        ll = half * 16 + l
                        qv = int((q[ll] >> shift) & 3) - (0 if (hmask[ll] & m) else 4)
                        y[i] = d * sc * qv
                        i += 1
                m <<= 1
        out.append(y)
    return np.concatenate(out)


def _gsm(j, sb):
    if j < 4:
        return sb[j] & 63, sb[j + 4] & 63
    return ((sb[j + 4] & 0xF) | ((sb[j - 4] >> 6) << 4),
            (sb[j + 4] >> 4) | ((sb[j] >> 6) << 4))


def ref_q4_k(raw):
    out = []
    for blk in raw.reshape(-1, 144):
        d = np.frombuffer(blk[0:2].tobytes(), np.float16)[0].astype(np.float32)
        dmin = np.frombuffer(blk[2:4].tobytes(), np.float16)[0].astype(np.float32)
        sb = blk[4:16]
        qs = blk[16:]
        y = np.zeros(256, np.float32)
        i = 0
        is_ = 0
        qoff = 0
        for j in range(0, 256, 64):
            sc1, m1 = _gsm(is_, sb)
            sc2, m2 = _gsm(is_ + 1, sb)
            for l in range(32):
                y[i] = d * sc1 * (qs[qoff + l] & 0xF) - dmin * m1; i += 1
            for l in range(32):
                y[i] = d * sc2 * (qs[qoff + l] >> 4) - dmin * m2; i += 1
            qoff += 32
            is_ += 2
        out.append(y)
    return np.concatenate(out)


def ref_q5_k(raw):
    out = []
    for blk in raw.reshape(-1, 176):
        d = np.frombuffer(blk[0:2].tobytes(), np.float16)[0].astype(np.float32)
        dmin = np.frombuffer(blk[2:4].tobytes(), np.float16)[0].astype(np.float32)
        sb = blk[4:16]
        qh = blk[16:48]
        ql = blk[48:]
        y = np.zeros(256, np.float32)
        i = 0
        is_ = 0
        qoff = 0
        u1, u2 = 1, 2
        for j in range(0, 256, 64):
            sc1, m1 = _gsm(is_, sb)
            sc2, m2 = _gsm(is_ + 1, sb)
            for l in range(32):
                q = (ql[qoff + l] & 0xF) + (16 if (qh[l] & u1) else 0)
                y[i] = d * sc1 * q - dmin * m1; i += 1
            for l in range(32):
                q = (ql[qoff + l] >> 4) + (16 if (qh[l] & u2) else 0)
                y[i] = d * sc2 * q - dmin * m2; i += 1
            qoff += 32
            is_ += 2
            u1 <<= 2
            u2 <<= 2
        out.append(y)
    return np.concatenate(out)


def ref_q6_k(raw):
    out = []
    for blk in raw.reshape(-1, 210):
        ql = blk[:128]
        qh = blk[128:192]
        scales = blk[192:208].view(np.int8)
        d = np.frombuffer(blk[208:210].tobytes(), np.float16)[0].astype(np.float32)
        y = np.zeros(256, np.float32)
        yo, lo, ho, so = 0, 0, 0, 0
        for n in (0, 128):
            for l in range(32):
                is_ = l // 16
                q1 = int((ql[lo + l] & 0xF) | (((qh[ho + l] >> 0) & 3) << 4)) - 32
                q2 = int((ql[lo + l + 32] & 0xF) | (((qh[ho + l] >> 2) & 3) << 4)) - 32
                q3 = int((ql[lo + l] >> 4) | (((qh[ho + l] >> 4) & 3) << 4)) - 32
                q4 = int((ql[lo + l + 32] >> 4) | (((qh[ho + l] >> 6) & 3) << 4)) - 32
                y[yo + l] = d * scales[so + is_] * q1
                y[yo + l + 32] = d * scales[so + is_ + 2] * q2
                y[yo + l + 64] = d * scales[so + is_ + 4] * q3
                y[yo + l + 96] = d * scales[so + is_ + 6] * q4
            yo += 128
            lo += 64
            ho += 32
            so += 8
        out.append(y)
    return np.concatenate(out)


# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fn_vec,fn_ref,block_bytes", [
    (DQ.dq_q2_k, ref_q2_k, 84),
    (DQ.dq_q3_k, ref_q3_k, 110),
    (DQ.dq_q4_k, ref_q4_k, 144),
    (DQ.dq_q5_k, ref_q5_k, 176),
    (DQ.dq_q6_k, ref_q6_k, 210),
])
def test_kquant_vectorised_matches_scalar(fn_vec, fn_ref, block_bytes):
    raw = rng.integers(0, 256, size=4 * block_bytes, dtype=np.uint8)
    # avoid inf/NaN from random f16 scale bytes: zero the exponent top bits
    # of d/dmin candidates is fiddly; instead accept inf-free check by
    # filtering non-finite lanes identically in both impls
    v = fn_vec(raw)
    r = fn_ref(raw)
    mask = np.isfinite(r)
    np.testing.assert_allclose(v[mask], r[mask], rtol=1e-5, atol=1e-5)
    assert (np.isfinite(v) == mask).all()


def test_q8_0_roundtrip():
    x = rng.standard_normal(32 * 64).astype(np.float32)
    raw = np.frombuffer(W.quantize_q8_0(x), np.uint8)
    y = DQ.dq_q8_0(raw)
    err = np.abs(x - y).max() / np.abs(x).max()
    assert err < 0.01


def test_q4_0_roundtrip():
    x = rng.standard_normal(32 * 64).astype(np.float32)
    raw = np.frombuffer(W.quantize_q4_0(x), np.uint8)
    y = DQ.dq_q4_0(raw)
    err = np.abs(x - y).mean() / np.abs(x).mean()
    assert err < 0.2  # 4-bit is lossy


def test_q5_0_layout():
    """Hand-built block: d=1.0, all nibbles + high bits set to known values."""
    d = np.float16(1.0).tobytes()
    qh = (0b10101010101010101010101010101010).to_bytes(4, "little")
    qs = bytes([0x21] * 16)  # low nibble 1, high nibble 2
    raw = np.frombuffer(d + qh + qs, np.uint8)
    y = DQ.dq_q5_0(raw)
    # elem 0: q = 1 | (bit0=0)<<4 = 1 → 1-16 = -15
    assert y[0] == -15.0
    # elem 1: q = 1 | (bit1=1)<<4 = 17 → 1
    assert y[1] == 1.0
    # elem 16: q = 2 | (bit16=0)<<4 → 2-16 = -14
    assert y[16] == -14.0
    assert y[17] == 2.0 - 16.0 + 16.0  # bit17=1 → 18-16 = 2


def test_writer_reader_roundtrip(tmp_path):
    path = str(tmp_path / "t.gguf")
    w = W.GGUFWriter(path)
    w.add_meta("general.architecture", "llama")
    w.add_meta("llama.block_count", 2)
    w.add_meta("llama.rope.freq_base", 10000.0)
    w.add_meta("tokenizer.ggml.tokens", ["<s>", "</s>", "hello"])
    w.add_meta("tokenizer.ggml.scores", [0.0, -1.0, -2.0])
    w.add_meta("tokenizer.ggml.bos_token_id", 0)
    w.add_meta("some.flag", True)
    a = rng.standard_normal((8, 16)).astype(np.float32)
    b = rng.standard_normal((4, 32)).astype(np.float32)
    w.add_tensor_f32("a.weight", a)
    w.add_tensor_f16("b.weight", b)
    qx = rng.standard_normal(64).astype(np.float32)
    w.add_tensor_raw("c.weight", (2, 32), R.GGML_Q8_0, W.quantize_q8_0(qx))
    w.write()

    with R.GGUFFile(path) as f:
        assert f.arch == "llama"
        assert f.field("block_count") == 2
        assert f.field("rope.freq_base") == pytest.approx(10000.0)
        assert f.metadata["tokenizer.ggml.tokens"] == ["<s>", "</s>", "hello"]
        assert f.metadata["some.flag"] is True
        ta = f.tensors["a.weight"]
        assert ta.shape == (8, 16)
        np.testing.assert_array_equal(
            DQ.dequantize_tensor(f, ta), a)
        tb = f.tensors["b.weight"]
        np.testing.assert_allclose(
            DQ.dequantize_tensor(f, tb), b, atol=1e-3)
        tc = f.tensors["c.weight"]
        yc = DQ.dequantize_tensor(f, tc)
        assert yc.shape == (2, 32)
        assert np.abs(yc.reshape(-1) - qx).max() < 0.05


def test_unsupported_type_raises():
    with pytest.raises(NotImplementedError):
        DQ.dequantize(np.zeros(16, np.uint8), 99, (16,))


# ---------------------------------------------------------------------------
# i-quants (iq4_nl / iq4_xs): scalar references straight from ggml's
# dequantize_row_iq4_nl/_xs (the llama.cpp math inside the image the
# reference delegates to), plus hand-built layout pins.
# ---------------------------------------------------------------------------

_KVALS = [-127, -104, -83, -65, -49, -35, -22, -10,
          1, 13, 25, 38, 53, 69, 89, 113]


def ref_iq4_nl(raw):
    out = []
    for blk in raw.reshape(-1, 18):
        d = np.frombuffer(blk[0:2].tobytes(), np.float16)[0].astype(np.float32)
        qs = blk[2:]
        y = np.zeros(32, np.float32)
        for j in range(16):
            y[j] = d * _KVALS[qs[j] & 0xF]
            y[j + 16] = d * _KVALS[qs[j] >> 4]
        out.append(y)
    return np.concatenate(out)


def ref_iq4_xs(raw):
    out = []
    for blk in raw.reshape(-1, 136):
        d = np.frombuffer(blk[0:2].tobytes(), np.float16)[0].astype(np.float32)
        scales_h = int(np.frombuffer(blk[2:4].tobytes(), np.uint16)[0])
        scales_l = blk[4:8]
        qs = blk[8:]
        y = np.zeros(256, np.float32)
        for ib in range(8):
            ls = (int(scales_l[ib // 2] >> (4 * (ib % 2))) & 0xF) \
                 | (((scales_h >> (2 * ib)) & 3) << 4)
            dl = d * (ls - 32)
            for j in range(16):
                y[ib * 32 + j] = dl * _KVALS[qs[ib * 16 + j] & 0xF]
                y[ib * 32 + j + 16] = dl * _KVALS[qs[ib * 16 + j] >> 4]
        out.append(y)
    return np.concatenate(out)


@pytest.mark.parametrize("fn_vec,fn_ref,block_bytes", [
    (DQ.dq_iq4_nl, ref_iq4_nl, 18),
    (DQ.dq_iq4_xs, ref_iq4_xs, 136),
])
def test_iq4_vectorised_matches_scalar(fn_vec, fn_ref, block_bytes):
    raw = rng.integers(0, 256, size=4 * block_bytes, dtype=np.uint8)
    v = fn_vec(raw)
    r = fn_ref(raw)
    mask = np.isfinite(r)
    np.testing.assert_allclose(v[mask], r[mask], rtol=1e-5, atol=1e-5)
    assert (np.isfinite(v) == mask).all()


def test_iq4_nl_layout():
    """d=2.0, byte 0x80 → low nibble 0 (LUT -127), high nibble 8 (LUT 1)."""
    d = np.float16(2.0).tobytes()
    raw = np.frombuffer(d + bytes([0x80] * 16), np.uint8)
    y = DQ.dq_iq4_nl(raw)
    assert y[0] == 2.0 * -127
    assert y[16] == 2.0 * 1


def test_iq4_xs_layout():
    """Known 6-bit sub-block scales: ls for ib=0 comes from scales_l[0]
    low nibble | scales_h bits 0-1 << 4."""
    d = np.float16(1.0).tobytes()
    scales_h = (0b01).to_bytes(2, "little")      # ib0 high bits = 1
    scales_l = bytes([0x05, 0, 0, 0])            # ib0 low nibble = 5
    qs = bytes([0x08] * 128)                     # low nib 8 (LUT 1), high 0
    raw = np.frombuffer(d + scales_h + scales_l + qs, np.uint8)
    y = DQ.dq_iq4_xs(raw)
    ls0 = (5 | (1 << 4)) - 32                    # = -11
    assert y[0] == ls0 * 1.0                     # LUT[8] = 1
    assert y[16] == ls0 * -127.0                 # LUT[0] = -127
    # ib>=1: ls = 0 - 32 = -32
    assert y[32] == -32 * 1.0


def test_iq4_transcode_path(tmp_path):
    """A registry-style tag quantized iq4_nl transcodes end to end."""
    x = rng.standard_normal((2, 64)).astype(np.float32) * 0.1
    # quantize per ggml: per 32-block scale d = max/|LUT max|-ish; use a
    # crude nearest-code search (the spec only fixes DEQUANT semantics)
    blocks = x.reshape(-1, 32)
    raws = []
    for blk in blocks:
        d = float(np.abs(blk).max() / 113.0) or 1.0
        codes = np.argmin(
            np.abs(blk[:, None] / d - np.array(_KVALS)[None, :]), axis=1)
        lo, hi = codes[:16], codes[16:]
        raws.append(np.float16(d).tobytes()
                    + bytes((lo | (hi << 4)).astype(np.uint8)))
    raw = b"".join(raws)
    path = str(tmp_path / "iq.gguf")
    w = W.GGUFWriter(path)
    w.add_meta("general.architecture", "llama")
    w.add_tensor_raw("t.weight", (2, 64), R.GGML_IQ4_NL, raw)
    w.write()
    with R.GGUFFile(path) as f:
        y = DQ.dequantize_tensor(f, f.tensors["t.weight"])
    assert y.shape == (2, 64)
    err = np.abs(y - x).mean() / np.abs(x).mean()
    assert err < 0.1                              # 4-bit non-linear grid


def test_codebook_iquants_fail_loudly():
    """IQ1/IQ2/IQ3 decode through searched codebooks that only exist as
    llama.cpp source tables — unavailable in this build env (no vendored
    llama.cpp, zero egress). The honest behavior is a loud, actionable
    error at transcode time, never an approximated grid that would
    silently produce wrong weights (recorded blocker, round 5)."""
    for t, name in ((R.GGML_IQ2_XXS, "IQ2_XXS"), (R.GGML_IQ2_XS, "IQ2_XS"),
                    (R.GGML_IQ3_XXS, "IQ3_XXS"), (R.GGML_IQ3_S, "IQ3_S"),
                    (R.GGML_IQ1_S, "IQ1_S"), (R.GGML_IQ1_M, "IQ1_M"),
                    (R.GGML_IQ2_S, "IQ2_S")):
        with pytest.raises(NotImplementedError) as ei:
            DQ.dequantize(np.zeros(128, np.uint8), t, (256,))
        assert name in str(ei.value) and "codebook" in str(ei.value)
