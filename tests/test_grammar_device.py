"""Device-table grammar decode (the cause="grammar" retirement): the
GrammarTable BFS closure, engine-level device-vs-host bit parity —
including on-device escapes, the host-length rollback, and re-entry —
and the chunk-budget split between device-table and host-masked slots.

The scheduler-level acceptance (constrained traffic double-buffering
with the fallback counter pinned at 0) lives in test_paged_async.py;
this file pins the mechanism underneath it.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.ops.constrain import (
    INITIAL_STATE, GrammarTable, JsonConstraint, advance_bytes)
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from test_constrain import EOS, PIECES, make_table

CHUNK = 4


@pytest.fixture(scope="module")
def table():
    return make_table()


@pytest.fixture(scope="module")
def gt(table):
    return GrammarTable.for_table(table, cap=64)


@pytest.fixture(scope="module")
def params():
    cfg = cfglib.PRESETS["tiny"]
    return decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)


def _engine(params):
    cfg = cfglib.PRESETS["tiny"]
    return Engine(cfg, params,
                  ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                    cache_dtype=jnp.float32,
                                    min_prefill_bucket=16,
                                    decode_chunk=CHUNK))


# --- GrammarTable closure ----------------------------------------------------

def test_grammar_table_masks_match_pda(table, gt):
    """Every tabled row is exactly mask_for of its packed state, and the
    BFS root is the start state."""
    assert gt.states[0] == INITIAL_STATE
    assert 1 < gt.n_states <= 64
    for g, st in enumerate(gt.states):
        np.testing.assert_array_equal(gt.mask[g], table.mask_for(st))


def test_grammar_table_transitions_exact(table, gt):
    """trans[g, t] is the id of advance_bytes(state_g, piece_t) for every
    mask-allowed non-EOG token, and -1 (escape) everywhere else."""
    for g, st in enumerate(gt.states):
        allowed = np.asarray(table.mask_for(st))
        for tid, piece in enumerate(table.pieces):
            bit = (allowed[tid >> 5] >> np.uint32(tid & 31)) & 1
            nid = int(gt.trans[g, tid])
            if not bit or tid in set(table.eog_ids) or not piece:
                assert nid == -1, (g, tid)
                continue
            ns = advance_bytes(st, piece)
            if nid < 0:
                # escape: either the PDA rejected it (impossible for a
                # masked-in token) or the target state is beyond cap
                assert ns is not None and gt.state_id(ns) == -1, (g, tid)
            else:
                assert nid < gt.n_states
                assert gt.states[nid] == ns, (g, tid)


def test_grammar_table_cap_and_cache(table):
    small = GrammarTable.for_table(table, cap=4)
    assert small.n_states <= 4
    assert (small.trans < 4).all()           # never points beyond cap
    assert small is GrammarTable.for_table(table, cap=4)   # cached
    assert small is not GrammarTable.for_table(table, cap=64)
    assert small.state_id(INITIAL_STATE) == 0
    assert small.state_id(None) == -1
    assert small.state_id(b"\xff\xff not a state") == -1


def test_install_grammar_guards(params, gt, monkeypatch):
    eng = _engine(params)
    assert eng.install_grammar(("g", 1), gt.mask, gt.trans)
    assert eng.install_grammar(("g", 1), gt.mask, gt.trans)   # same key
    # a DIFFERENT table swaps freely while no slot is in device mode...
    assert eng.install_grammar(("g", 2), gt.mask, gt.trans)
    # ...but not under a live device-mode slot
    eng._gdev_mode[0] = True
    assert not eng.install_grammar(("g", 3), gt.mask, gt.trans)
    assert eng.install_grammar(("g", 2), gt.mask, gt.trans)   # still live
    eng._gdev_mode[0] = False
    monkeypatch.setattr(eng, "_grammar_device", False)
    assert not eng.install_grammar(("g", 4), gt.mask, gt.trans)


def test_step_budgets_split(params, gt):
    """Host-masked constrained slots step 1 token per dispatch;
    device-table slots keep the full chunk."""
    eng = _engine(params)
    eng._constrained[0] = True                 # host-masked
    eng._constrained[1] = True
    eng._gdev_mode[1] = True                   # device-table
    np.testing.assert_array_equal(eng.step_budgets(CHUNK), [1, CHUNK])


# --- engine device-vs-host bit parity ---------------------------------------

def _host_run(params, table, seed, max_steps=63):
    """Reference: host PDA mask refreshed every token (1-token budget
    comes from step_budgets in the scheduler; here we just re-mask per
    chunk row 0 and step chunk-by-chunk on slot 1)."""
    eng = _engine(params)
    opts = SlotOptions(temperature=0.9, seed=seed, repeat_penalty=1.0)
    c = JsonConstraint(table)
    first = eng.admit(1, np.array([7, 7], np.int32), opts,
                      mask_row=c.mask_row())
    assert c.advance(first)
    eng.set_mask(1, c.mask_row())
    out = [int(first)]
    for _ in range(max_steps):
        t = int(eng.decode()[1])
        out.append(t)
        if t == EOS:
            break
        assert c.advance(t), (t, out)
        eng.set_mask(1, c.mask_row())
    return out


def _device_run(params, table, gt, seed, max_toks=64):
    """Device-table run with the scheduler's host mirror: consume chunk
    rows while the device automaton stayed in-table; on escape, roll the
    over-advance back through spec_ack and re-install the exact mask
    (re-entering device mode when the PDA state is tabled again)."""
    eng = _engine(params)
    assert eng.install_grammar(("parity", id(gt)), gt.mask, gt.trans)
    opts = SlotOptions(temperature=0.9, seed=seed, repeat_penalty=1.0)
    c = JsonConstraint(table)
    first = eng.admit(1, np.array([7, 7], np.int32), opts,
                      mask_row=c.mask_row())
    assert c.advance(first)
    gid = gt.state_id(c.state)
    assert gid >= 0
    eng.set_mask(1, c.mask_row(), gid=gid)
    dev_mode = True
    out = [int(first)]
    escapes = 0
    done = False
    while not done and len(out) < max_toks:
        toks = eng.decode_n(CHUNK)
        if not dev_mode:
            # HOST-masked chunk: step_budgets froze the slot after row 0
            # (rows >= 1 are stale-mask resamples, nothing to roll back)
            t = int(toks[0, 1])
            out.append(t)
            if t == EOS:
                break
            assert c.advance(t), (t, out)
            gid = gt.state_id(c.state)
            dev_mode = gid >= 0
            eng.set_mask(1, c.mask_row(), gid=gid)
            continue
        st = gt.state_id(c.state)
        for r in range(CHUNK):
            t = int(toks[r, 1])
            if t == EOS:
                out.append(t)
                done = True
                break
            nid = int(gt.trans[st, t]) if st >= 0 else -1
            assert c.advance(t), (r, t, out)
            out.append(t)
            if nid < 0:
                # device escaped after consuming t: remaining rows are
                # garbage — reconcile lengths, re-mask, maybe re-enter
                escapes += 1
                ns = gt.state_id(c.state)
                eng.spec_ack(np.array([0, CHUNK - (r + 1)], np.int64))
                dev_mode = ns >= 0
                eng.set_mask(1, c.mask_row(), gid=ns if ns >= 0 else -1)
                break
            st = nid
    return out, escapes


@pytest.mark.parametrize("seed", [0, 5, 7])
def test_device_grammar_bit_parity(params, table, gt, seed):
    ref = _host_run(params, table, seed)
    got, escapes = _device_run(params, table, gt, seed)
    assert got == ref, (seed, got, ref)
    data = b"".join(PIECES[t] for t in got if t != EOS)
    assert advance_bytes(INITIAL_STATE, data) is not None
    if got[-1] == EOS:
        json.loads(data.decode())    # EOS stop ⇒ complete JSON value
    # seed 5 wanders into an unbounded string tail on this model build —
    # the escape/rollback/re-entry path MUST be covered, not just the
    # stay-in-table happy path
    if seed == 5:
        assert escapes >= 1


def test_escape_freezes_slot_on_device(params, table, gt):
    """After an in-chunk escape the device automaton reports -2 and the
    slot's device length matches the host's post-rollback view — the
    frozen rows never advanced it."""
    eng = _engine(params)
    assert eng.install_grammar(("freeze", id(gt)), gt.mask, gt.trans)
    opts = SlotOptions(temperature=0.9, seed=5, repeat_penalty=1.0)
    c = JsonConstraint(table)
    first = eng.admit(1, np.array([7, 7], np.int32), opts,
                      mask_row=c.mask_row())
    assert c.advance(first)
    eng.set_mask(1, c.mask_row(), gid=gt.state_id(c.state))
    for _ in range(16):
        toks = eng.decode_n(CHUNK)
        gstate = int(np.asarray(eng._fetch(eng._gstate))[1])
        st = gt.state_id(c.state)
        for r in range(CHUNK):
            t = int(toks[r, 1])
            if t == EOS:
                return            # finished without escaping: fine
            nid = int(gt.trans[st, t]) if st >= 0 else -1
            assert c.advance(t)
            if nid < 0:
                assert gstate == -2         # frozen on device
                over = CHUNK - (r + 1)
                eng.spec_ack(np.array([0, over], np.int64))
                # frozen rows never advanced the device length: after the
                # rollback the host mirror agrees with the device
                lens = np.asarray(eng._fetch(eng.lengths))
                assert int(lens[1]) == int(eng._host_lengths[1])
                return
            st = nid
    pytest.skip("seed never escaped on this model build")
