"""Invariant linter (tools/invariant_lint).

Each pass is proven against a miniature fixture tree under
tests/fixtures/lint/ with seeded violations — exact finding counts,
messages, and suppression behavior — and the shipped tree itself must
lint clean (zero unsuppressed findings), which is the CI gate's
contract.
"""

import json
from pathlib import Path

import pytest

from tools.invariant_lint import ALL_PASSES, LintConfig, run_passes
from tools.invariant_lint.core import (render_github, render_json,
                                       render_summary_markdown, summarize)
from tools.invariant_lint.passes import (DeterminismPass,
                                         ExceptionHygienePass,
                                         FaultCatalogPass,
                                         FollowerPurityPass, HostSyncPass,
                                         KnobRegistryPass, LockOrderPass,
                                         MetricsDisciplinePass)

REPO = Path(__file__).resolve().parents[1]
FIX = REPO / "tests" / "fixtures" / "lint"


def fixture_config(case, **overrides):
    defaults = dict(
        root=FIX / case,
        code_roots=("pkg",),
        knobs_module="pkg/knobs.py",
        docs_roots=("docs/en", "docs/zh"),
        metrics_module="pkg/metrics.py",
        hot_roots=(("pkg/engine.py", "decode_n_launch"),),
        graph_scopes=("pkg",),
        follower_module="pkg/follower.py",
        determinism_modules=("pkg/engine.py",),
        exception_scopes=("pkg",),
        faults_module="pkg/faults.py",
    )
    defaults.update(overrides)
    return LintConfig(**defaults)


def run_one(case, pass_obj, **overrides):
    cfg = fixture_config(case, **overrides)
    return run_passes(cfg, [pass_obj])


def unsuppressed(findings):
    return [f for f in findings if not f.suppressed]


# -- knob-registry ----------------------------------------------------------

def test_knob_registry_fixture():
    fs = run_one("knobs", KnobRegistryPass())
    live = unsuppressed(fs)
    msgs = [f.message for f in live]
    assert len(live) == 6, msgs
    assert sum("TPU_FIX_B is read here but not declared" in m
               for m in msgs) == 1
    assert sum("TPU_FIX_STALE is declared but no code mentions" in m
               for m in msgs) == 1
    assert sum("missing from the docs/en knob tables" in m
               for m in msgs) == 1          # TPU_FIX_STALE only
    assert sum("missing from the docs/zh knob tables" in m
               for m in msgs) == 2          # TPU_FIX_A + TPU_FIX_STALE
    assert sum("docs mention TPU_FIX_GHOST" in m for m in msgs) == 1
    # the suppressed undeclared read carries its reason
    supp = [f for f in fs if f.suppressed]
    assert len(supp) == 1
    assert supp[0].suppress_reason == "fixture exercises suppression"
    assert "TPU_FIX_SUPP" in supp[0].message


def test_knob_registry_read_sites_are_finding_anchors():
    fs = unsuppressed(run_one("knobs", KnobRegistryPass()))
    read = [f for f in fs if "TPU_FIX_B" in f.message][0]
    assert read.path == "pkg/mod.py"
    assert read.line == 8


# -- metrics-discipline -----------------------------------------------------

def test_metrics_discipline_fixture():
    fs = unsuppressed(run_one("metrics", MetricsDisciplinePass()))
    msgs = [f.message for f in fs]
    assert len(fs) == 3, msgs
    assert sum("tpu_model_fix_missing_total is used but never described"
               in m for m in msgs) == 1
    assert sum("tpu_model_fix_missing_total is incremented but never "
               "pre-seeded" in m for m in msgs) == 1
    assert sum("label keys {other}" in m for m in msgs) == 1
    # both seed idioms (batch loop + literal combos) satisfied the rest
    assert not any("fix_ok_total" in m for m in msgs)


# -- host-sync-hot-path -----------------------------------------------------

def test_host_sync_fixture():
    fs = run_one("hotsync", HostSyncPass())
    live = unsuppressed(fs)
    msgs = [f.message for f in live]
    assert len(live) == 3, msgs
    assert sum(".item()" in m for m in msgs) == 1
    assert sum("np.asarray" in m for m in msgs) == 1
    assert sum("int(x[...])" in m for m in msgs) == 1
    # every live finding sits in the reachable helper, none in cold()
    assert all("_helper" in m for m in msgs)
    supp = [f for f in fs if f.suppressed]
    assert len(supp) == 1 and "block_until_ready" in supp[0].message


# -- lock-order -------------------------------------------------------------

def test_lock_order_fixture():
    fs = unsuppressed(run_one("lockorder", LockOrderPass()))
    msgs = [f.message for f in fs]
    cycle = [m for m in msgs if "lock-order cycle" in m]
    blocking = [m for m in msgs if "while holding" in m
                and "cycle" not in m]
    assert len(cycle) == 2, msgs          # A->B and B->A edges
    assert any("A._la" in m and "B._lb" in m for m in cycle)
    assert len(blocking) == 2, msgs
    assert sum("time.sleep" in m for m in blocking) == 1
    assert sum("socket sendall (via A._push)" in m
               for m in blocking) == 1
    # the RLock re-entry produced nothing
    assert not any("R._lr" in m for m in msgs)


# -- follower-purity --------------------------------------------------------

def test_follower_purity_fixture():
    fs = unsuppressed(run_one("follower", FollowerPurityPass()))
    assert len(fs) == 1, [f.message for f in fs]
    f = fs[0]
    assert "FLIGHT" in f.message
    assert f.path == "pkg/follower.py"
    # flagged in the helper the handler reaches, not in unrelated()
    assert f.line == 13


# -- determinism ------------------------------------------------------------

def test_determinism_fixture():
    fs = unsuppressed(run_one("determinism", DeterminismPass()))
    msgs = [f.message for f in fs]
    assert len(fs) == 4, msgs
    assert sum("time.time()" in m for m in msgs) == 1
    assert sum("random.random" in m for m in msgs) == 1
    assert sum("a set literal" in m for m in msgs) == 1
    assert sum("the set 'PAGES'" in m for m in msgs) == 1


# -- exception-hygiene ------------------------------------------------------

def test_exception_hygiene_fixture():
    fs = run_one("exceptions", ExceptionHygienePass())
    live = unsuppressed(fs)
    by_pass = {}
    for f in live:
        by_pass.setdefault(f.pass_id, []).append(f)
    assert len(by_pass.get("exception-hygiene", [])) == 2   # bare + swallow
    # the reasonless allow() is itself a finding
    assert len(by_pass.get("suppression", [])) == 1
    assert "no reason string" in by_pass["suppression"][0].message
    supp = [f for f in fs if f.suppressed]
    assert len(supp) == 2
    reasons = {f.suppress_reason for f in supp}
    assert "fixture-justified teardown" in reasons
    assert None in reasons                                  # the reasonless one


# -- fault-catalog ----------------------------------------------------------

def test_fault_catalog_fixture():
    fs = run_one("faults", FaultCatalogPass())
    live = unsuppressed(fs)
    msgs = [f.message for f in live]
    assert len(live) == 4, msgs
    assert sum('"fix.ghost" is checked here but not registered' in m
               for m in msgs) == 1
    assert sum("computed point name" in m for m in msgs) == 1
    assert sum('"fix.stale" is registered but no' in m for m in msgs) == 1
    assert sum('"fix.nodoc" is registered but missing from the docs/zh'
               in m for m in msgs) == 1
    # healthy point produced nothing; suppression carries its reason
    assert not any("fix.ok" in m for m in msgs)
    supp = [f for f in fs if f.suppressed]
    assert len(supp) == 1
    assert supp[0].suppress_reason == "fixture exercises suppression"
    assert "fix.tolerated" in supp[0].message


def test_fault_catalog_finding_anchors():
    fs = unsuppressed(run_one("faults", FaultCatalogPass()))
    ghost = [f for f in fs if "fix.ghost" in f.message][0]
    assert ghost.path == "pkg/mod.py"
    stale = [f for f in fs if "fix.stale" in f.message][0]
    assert stale.path == "pkg/faults.py"


# -- output formats ---------------------------------------------------------

def test_json_schema_and_renderers():
    fs = run_one("exceptions", ExceptionHygienePass())
    doc = json.loads(render_json(ALL_PASSES, fs))
    assert doc["version"] == 1
    assert {r["id"] for r in doc["passes"]} == (
        {p.id for p in ALL_PASSES} | {"suppression", "parse"})
    for f in doc["findings"]:
        assert set(f) == {"path", "line", "pass", "severity", "message",
                          "suppressed", "suppress_reason"}
        assert isinstance(f["line"], int) and f["line"] >= 1
    gh = render_github(fs)
    assert "::error file=pkg/mod.py,line=" in gh
    assert "title=invariant-lint [exception-hygiene]" in gh
    # suppressed findings never become annotations
    assert gh.count("::error") == len(unsuppressed(fs))
    md = render_summary_markdown(ALL_PASSES, fs)
    assert "| `exception-hygiene` |" in md and "gate fails" in md


def test_pass_ids_unique_and_kebab():
    ids = [p.id for p in ALL_PASSES]
    assert len(ids) == len(set(ids)) == 8
    for pid in ids:
        assert pid == pid.lower() and " " not in pid


# -- the shipped tree is the contract ---------------------------------------

def test_shipped_tree_has_zero_unsuppressed_findings():
    fs = run_passes(LintConfig(root=REPO), ALL_PASSES)
    live = unsuppressed(fs)
    assert not live, "\n".join(f.render() for f in live)
    # every suppression in the tree carries a justification
    assert all(f.suppress_reason for f in fs if f.suppressed)


def test_shipped_tree_exercises_every_suppressible_pass():
    """The suppression policy is load-bearing: the tree documents its
    intentional violations rather than hiding them, so the passes that
    have known-intentional sites must show suppressed findings."""
    fs = run_passes(LintConfig(root=REPO), ALL_PASSES)
    rows = {r["id"]: r for r in summarize(ALL_PASSES, fs)}
    for pid in ("host-sync-hot-path", "lock-order", "follower-purity",
                "exception-hygiene"):
        assert rows[pid]["suppressed"] > 0, pid
        assert rows[pid]["findings"] == 0, pid


def test_every_tpu_knob_read_is_declared_and_documented():
    """Acceptance: 100% of TPU_* env reads declared in runtime/knobs.py
    and present in both docs trees (the knob-registry pass emits nothing
    at all on the shipped tree)."""
    fs = run_passes(LintConfig(root=REPO), [KnobRegistryPass()])
    assert not fs, "\n".join(f.render() for f in fs)


def test_every_fault_check_site_is_catalogued_and_documented():
    """Acceptance: every FAULTS.check site in the shipped tree names a
    registered catalog point, and both docs trees' fault-point tables
    list every point — so the chaos campaign's `FAULTS.points()` draw
    really covers every recovery path in the code."""
    fs = run_passes(LintConfig(root=REPO), [FaultCatalogPass()])
    assert not fs, "\n".join(f.render() for f in fs)
    from ollama_operator_tpu.runtime.faults import CATALOG, FAULTS
    assert [p.name for p in FAULTS.points()] == sorted(CATALOG)
    assert len(CATALOG) >= 12


def test_registry_importable_and_nonempty():
    from ollama_operator_tpu.runtime import knobs
    assert len(knobs.REGISTRY) >= 80
    k = knobs.lookup("TPU_DECODE_CHUNK")
    assert k is not None and k.subsystem == "engine"
    with pytest.raises(ValueError):
        knobs.declare("TPU_DECODE_CHUNK", "int", 0, "engine", "dup")
    assert [x.name for x in knobs.all_knobs()] == sorted(knobs.REGISTRY)
