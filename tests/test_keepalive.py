"""keep_alive semantics: duration parsing, the idle-unload reaper, the
`ollama stop` path (empty prompt + keep_alive 0), and /api/ps expiry."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import EngineConfig
from ollama_operator_tpu.server.app import (ApiError, ModelManager,
                                            parse_keep_alive)

from test_transcode import write_tiny_llama_gguf


def test_parse_keep_alive():
    assert parse_keep_alive(300) == 300.0
    assert parse_keep_alive(0) == 0.0
    assert parse_keep_alive(-1) is None
    assert parse_keep_alive("5m") == 300.0
    assert parse_keep_alive("1h30m") == 5400.0
    assert parse_keep_alive("300ms") == pytest.approx(0.3)
    assert parse_keep_alive("10") == 10.0
    assert parse_keep_alive("-1") is None
    assert parse_keep_alive("-5m") is None
    assert parse_keep_alive("1.5h") == 5400.0
    for bad in ("", "abc", "5x", None, True,
                "nan", "inf", float("nan"), float("inf")):
        with pytest.raises(ValueError):
            parse_keep_alive(bad)


@pytest.fixture()
def mgr(tmp_path):
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    base = str(tmp_path / "base.gguf")
    write_tiny_llama_gguf(base, cfg, params)
    m = ModelManager(str(tmp_path / "store"),
                     cache_dir=str(tmp_path / "cache"),
                     ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                       cache_dtype=jnp.float32,
                                       min_prefill_bucket=16),
                     engine_dtype="float32",
                     default_keep_alive="200ms")
    m.create("tiny", f"FROM {base}")
    yield m
    m.shutdown()


def test_idle_reaper_unloads_after_expiry(mgr):
    lm = mgr.require_loaded("tiny")
    r = lm.generate("hello", options={"num_predict": 2,
                                      "temperature": 0.0})
    assert r.generated_tokens >= 1
    deadline = time.time() + 15
    while mgr.loaded is not None and time.time() < deadline:
        time.sleep(0.2)
    assert mgr.loaded is None  # reaper fired after the 200ms keep_alive
    # a new request transparently reloads
    lm2 = mgr.require_loaded("tiny", keep_alive="1h")
    assert mgr.loaded is lm2
    assert mgr.expires_at is not None


def test_request_keep_alive_overrides_default(mgr):
    mgr.require_loaded("tiny", keep_alive="1h")
    time.sleep(2.5)  # several reaper ticks past the 200ms default
    assert mgr.loaded is not None
    # forever
    mgr.require_loaded("tiny", keep_alive=-1)
    assert mgr.expires_at is None
    ps = mgr.ps()
    assert ps[0]["expires_at"] == "0001-01-01T00:00:00Z"
    # bad value -> 400
    with pytest.raises(ApiError):
        mgr.require_loaded("tiny", keep_alive="banana")


def test_stop_unloads_resident_model(mgr):
    mgr.require_loaded("tiny", keep_alive="1h")
    assert mgr.stop("nope") is False
    assert mgr.loaded is not None
    assert mgr.stop("tiny") is True
    assert mgr.loaded is None
    assert mgr.ps() == []


def test_ps_reports_future_expiry(mgr):
    mgr.require_loaded("tiny", keep_alive="1h")
    ps = mgr.ps()
    assert len(ps) == 1
    from datetime import datetime, timezone
    exp = datetime.fromisoformat(ps[0]["expires_at"])
    secs = (exp - datetime.now(timezone.utc)).total_seconds()
    assert 3500 < secs < 3700
