"""Stream-preserving restart recovery, graceful drain, and the
hung-dispatch watchdog (the PR 9 lifecycle layer in runtime/scheduler.py).

The replay chaos drills here are the zero-error counterparts of the
exactly-once error drills in test_faults/test_paged_async/test_spec_decode
(which pin the fallback path with TPU_RESTART_REPLAY_MAX=0): with replay
ON, an engine failure mid-stream must be INVISIBLE to a deterministic
client — same tokens, same queue, no error frame — because the rebuilt
engine re-prefills prompt+generated through the preempt/resume machinery
and greedy/seeded sampling is bit-identical by construction (engine.py
seeds are slot-independent for opts.seed >= 0 and per-step keys fold in
the absolute position).
"""

import queue as queue_mod
import time

import numpy as np
import pytest

from ollama_operator_tpu.runtime.engine import SlotOptions
from ollama_operator_tpu.runtime.errors import DeadlineExceeded
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.scheduler import SchedulerBusy
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

from test_scheduler import GREEDY, make_stack

SEEDED = SlotOptions(temperature=0.9, seed=1234, repeat_penalty=1.0)
UNSEEDED = SlotOptions(temperature=0.9, repeat_penalty=1.0)   # seed=-1

PROMPT = np.array([5, 6], np.int32)


def _fail_decode_once(eng, fail_on=2):
    """Make the Nth decode entry raise (counting both the sync path and
    the async launch), then serve normally — one deterministic mid-stream
    engine failure, unlike an armed fail:after rule which fires forever."""
    calls = {"n": 0}
    real_decode_n = eng.decode_n
    real_launch = eng.decode_n_launch

    def flaky(n=None):
        calls["n"] += 1
        if calls["n"] == fail_on:
            raise RuntimeError("injected mid-stream failure")
        return real_decode_n(n)

    def flaky_launch(n=None):
        calls["n"] += 1
        if calls["n"] == fail_on:
            raise RuntimeError("injected mid-stream failure")
        return real_launch(n)

    eng.decode_n = flaky
    eng.decode_n_launch = flaky_launch
    return calls


def _reference(opts, max_tokens=24):
    """Uninterrupted run of PROMPT on a fresh stack."""
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        return list(sched.submit(PROMPT, opts, max_tokens=max_tokens)
                    .tokens())
    finally:
        sched.shutdown()


# -- replay: zero-error, bit-identical continuation --------------------

@pytest.mark.chaos
def test_replay_greedy_zero_errors_bit_identical():
    """Tentpole acceptance: a mid-stream engine failure with replay on
    is client-invisible for a greedy stream — the SAME output queue
    carries the SAME tokens, no error frame, and the replay counters
    account for the re-prefilled work."""
    ref = _reference(GREEDY)
    assert len(ref) >= 8                       # failure lands mid-stream
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    rr0 = METRICS.get("tpu_model_replayed_requests_total")
    rt0 = METRICS.get("tpu_model_replayed_tokens_total")
    try:
        _fail_decode_once(eng, fail_on=2)
        r = sched.submit(PROMPT, GREEDY, max_tokens=24)
        out = list(r.tokens())                 # must NOT raise
        assert out == ref
        assert r.error is None
        assert r.done_reason in ("stop", "length")
        with pytest.raises(queue_mod.Empty):   # stream is terminal
            r.out.get_nowait()
        assert sched.n_replays == 1
        assert sched.n_replay_fallbacks == 0
        assert sched.n_restarts == 1
        assert not sched.broken
        assert METRICS.get("tpu_model_replayed_requests_total") == rr0 + 1
        # token cost = prompt + generated-so-far at failure time
        assert METRICS.get("tpu_model_replayed_tokens_total") > rt0
    finally:
        sched.shutdown()


@pytest.mark.chaos
def test_replay_seeded_zero_errors_bit_identical():
    """Seeded sampling (opts.seed >= 0) is in the determinism contract:
    the base key is slot-independent and per-step keys fold in the
    absolute position, so replay continues byte-identical."""
    ref = _reference(SEEDED)
    assert len(ref) >= 8
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    try:
        _fail_decode_once(eng, fail_on=2)
        r = sched.submit(PROMPT, SEEDED, max_tokens=24)
        out = list(r.tokens())
        assert out == ref
        assert r.error is None
        assert sched.n_replays == 1
    finally:
        sched.shutdown()


@pytest.mark.chaos
def test_replay_both_streams_recover_and_new_work_serves():
    """Two concurrent greedy streams both replay after one failure, and
    the scheduler keeps serving fresh work afterwards."""
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    try:
        _fail_decode_once(eng, fail_on=2)
        reqs = [sched.submit(np.array([i + 1, i + 2], np.int32), GREEDY,
                             max_tokens=16) for i in range(2)]
        outs = [list(r.tokens()) for r in reqs]
        assert all(len(o) == 16 for o in outs)
        assert all(r.error is None for r in reqs)
        assert sched.n_replays == 2
        r2 = sched.submit(np.array([9], np.int32), GREEDY, max_tokens=3)
        assert len(list(r2.tokens())) == 3
    finally:
        sched.shutdown()


def test_replay_unseeded_sampling_errors_exactly_once():
    """Unseeded temperature sampling derives its RNG from (slot,
    seq_len) — not replayable. Fail-safe: today's exactly-one error
    frame, counted under cause="nondeterministic"."""
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    fb0 = METRICS.get("tpu_model_replay_fallback_total",
                      '{cause="nondeterministic"}')
    try:
        _fail_decode_once(eng, fail_on=2)
        r = sched.submit(PROMPT, UNSEEDED, max_tokens=24)
        with pytest.raises(RuntimeError, match="injected"):
            list(r.tokens())
        with pytest.raises(queue_mod.Empty):   # exactly once
            r.out.get_nowait()
        assert sched.n_replays == 0
        assert sched.n_replay_fallbacks == 1
        assert METRICS.get("tpu_model_replay_fallback_total",
                           '{cause="nondeterministic"}') == fb0 + 1
    finally:
        sched.shutdown()


def test_replay_over_budget_errors_exactly_once(monkeypatch):
    """ISSUE acceptance: a replay-ineligible failure (over the token
    budget) produces exactly ONE error, never a duplicate or a hang."""
    monkeypatch.setenv("TPU_RESTART_REPLAY_TOKENS", "1")
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    fb0 = METRICS.get("tpu_model_replay_fallback_total",
                      '{cause="over_budget"}')
    try:
        _fail_decode_once(eng, fail_on=2)
        r = sched.submit(PROMPT, GREEDY, max_tokens=24)
        with pytest.raises(RuntimeError, match="injected"):
            list(r.tokens())
        with pytest.raises(queue_mod.Empty):
            r.out.get_nowait()
        assert sched.n_replays == 0
        assert METRICS.get("tpu_model_replay_fallback_total",
                           '{cause="over_budget"}') == fb0 + 1
        # the loop recovered regardless: fresh work serves
        r2 = sched.submit(np.array([9], np.int32), GREEDY, max_tokens=3)
        assert len(list(r2.tokens())) == 3
    finally:
        sched.shutdown()


@pytest.mark.chaos
def test_replay_fault_point_forces_fallback():
    """scheduler.replay fail: the injected fault must push the stream
    down the fail-safe exactly-once error path (cause="faulted"), not
    crash the classification loop."""
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    fb0 = METRICS.get("tpu_model_replay_fallback_total",
                      '{cause="faulted"}')
    try:
        FAULTS.arm("scheduler.replay", "fail")
        _fail_decode_once(eng, fail_on=2)
        r = sched.submit(PROMPT, GREEDY, max_tokens=24)
        with pytest.raises(RuntimeError, match="injected mid-stream"):
            list(r.tokens())
        with pytest.raises(queue_mod.Empty):
            r.out.get_nowait()
        assert METRICS.get("tpu_model_replay_fallback_total",
                           '{cause="faulted"}') == fb0 + 1
    finally:
        FAULTS.disarm("scheduler.replay")
        sched.shutdown()


def test_replay_eligibility_classification():
    """The determinism contract, as a table."""
    from ollama_operator_tpu.runtime.scheduler import Scheduler

    class R:
        embeds = None
        opts = GREEDY

    r = R()
    assert Scheduler._replay_ineligible(r) is None          # greedy
    r.opts = SEEDED
    assert Scheduler._replay_ineligible(r) is None          # seeded
    r.opts = UNSEEDED
    assert Scheduler._replay_ineligible(r) == "nondeterministic"
    r.opts = SlotOptions(temperature=0.0, mirostat=2)
    assert Scheduler._replay_ineligible(r) == "nondeterministic"
    r.opts = GREEDY
    r.embeds = object()
    assert Scheduler._replay_ineligible(r) == "multimodal"


# -- graceful drain ----------------------------------------------------

def test_drain_sheds_new_submits_and_running_completes():
    """begin_drain: new submits shed 503 + Retry-After immediately;
    streams already running keep generating to completion."""
    cfg, params, eng, sched = make_stack(slots=1)
    ds0 = METRICS.get("tpu_model_drain_started_total")
    try:
        r = sched.submit(PROMPT, GREEDY, max_tokens=12)
        it = r.tokens()
        next(it)                                # running for sure
        sched.begin_drain()
        assert METRICS.get("tpu_model_drain_started_total") == ds0 + 1
        sched.begin_drain()                     # idempotent
        assert METRICS.get("tpu_model_drain_started_total") == ds0 + 1
        with pytest.raises(SchedulerBusy) as ei:
            sched.submit(np.array([9], np.int32), GREEDY, max_tokens=1)
        assert ei.value.retry_after_s >= 1
        rest = list(it)                         # finishes, not shed
        assert len(rest) >= 1
        assert r.done_reason in ("stop", "length")
        assert sched.lifecycle_stats()["state"] == "draining"
        # nothing left: drain returns without shedding anyone
        assert sched.drain(timeout_s=5) == 0
    finally:
        sched.shutdown()


def test_drain_timeout_sheds_stragglers():
    """drain(timeout) with an unbounded stream still running: the
    straggler gets a terminal ("done", "drain") frame (partial output
    stands) and waiting requests shed 503 with Retry-After."""
    cfg, params, eng, sched = make_stack(slots=1)
    sh0 = METRICS.get("tpu_model_drain_shed_total")
    try:
        r_run = sched.submit(PROMPT, GREEDY, max_tokens=10_000)
        it = r_run.tokens()
        next(it)                                # occupies the only slot
        # slow every decode step so the stream can't finish (or the
        # queued request get admitted) inside the drain window
        FAULTS.arm("engine.step", "delay:150ms")
        r_q = sched.submit(np.array([9], np.int32), GREEDY, max_tokens=4)
        shed = sched.drain(timeout_s=0.4)
        assert shed == 2
        assert METRICS.get("tpu_model_drain_shed_total") >= sh0 + 2
        list(it)                                # drains to the done frame
        assert r_run.done_reason == "drain"
        with pytest.raises(DeadlineExceeded) as ei:
            list(r_q.tokens())
        assert ei.value.while_queued
        assert ei.value.retry_after_s >= 1
        assert sched.n_active == 0
    finally:
        FAULTS.disarm("engine.step")
        sched.shutdown()


def test_drain_timeout_env_default(monkeypatch):
    from ollama_operator_tpu.runtime.scheduler import drain_timeout_s
    monkeypatch.delenv("TPU_DRAIN_TIMEOUT_S", raising=False)
    assert drain_timeout_s() == 30.0
    monkeypatch.setenv("TPU_DRAIN_TIMEOUT_S", "7.5")
    assert drain_timeout_s() == 7.5


# -- hung-dispatch watchdog --------------------------------------------

@pytest.mark.chaos
def test_watchdog_fires_and_replay_recovers(monkeypatch):
    """engine.watchdog delay (a wedged dispatch): the watchdog fires at
    its budget, the wait is abandoned, the supervisor restarts, and the
    stream REPLAYS to the same tokens an unwedged run produces."""
    ref = _reference(GREEDY, max_tokens=10)
    monkeypatch.setenv("TPU_DISPATCH_WATCHDOG_MS", "300")
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    wf0 = METRICS.get("tpu_model_watchdog_fires_total")
    try:
        # the wedge outlives the whole test: only the abandon path can
        # unblock the stream (the :once mode disarms it for the retry)
        FAULTS.arm("engine.watchdog", "delay:30s:once")
        t0 = time.monotonic()
        r = sched.submit(PROMPT, GREEDY, max_tokens=10)
        out = list(r.tokens())
        assert time.monotonic() - t0 < 20      # abandoned, not waited out
        assert out == ref
        assert r.error is None
        assert sched.n_watchdog_fires == 1
        assert sched.n_replays >= 1
        assert sched.n_restarts >= 1
        assert not sched.broken
        assert METRICS.get("tpu_model_watchdog_fires_total") == wf0 + 1
    finally:
        FAULTS.disarm("engine.watchdog")
        sched.shutdown()


def test_watchdog_timeout_knob(monkeypatch):
    cfg, params, eng, sched = make_stack(slots=1)
    try:
        monkeypatch.setenv("TPU_DISPATCH_WATCHDOG_MS", "0")
        assert sched._watchdog_timeout_s() == 0.0      # disabled
        monkeypatch.setenv("TPU_DISPATCH_WATCHDOG_MS", "2500")
        assert sched._watchdog_timeout_s() == 2.5
        monkeypatch.delenv("TPU_DISPATCH_WATCHDOG_MS")
        # auto mode: histogram-derived, clamped to [15s, 120s] — never
        # tighter than the 15s floor whatever this session observed
        assert 15.0 <= sched._watchdog_timeout_s() <= 120.0
    finally:
        sched.shutdown()


def test_watched_ferries_results_and_exceptions(monkeypatch):
    """_watched is transparent when nothing wedges: values return,
    exceptions re-raise on the scheduler thread."""
    monkeypatch.setenv("TPU_DISPATCH_WATCHDOG_MS", "5000")
    cfg, params, eng, sched = make_stack(slots=1)
    try:
        assert sched._watched(lambda: 42) == 42
        with pytest.raises(ValueError, match="boom"):
            sched._watched(lambda: (_ for _ in ()).throw(ValueError("boom")))
        # the persistent worker survives a ferried exception
        assert sched._watched(lambda: "ok") == "ok"
    finally:
        sched.shutdown()


# -- /api/ps lifecycle block ------------------------------------------

def test_lifecycle_stats_shape():
    cfg, params, eng, sched = make_stack(slots=1)
    try:
        st = sched.lifecycle_stats()
        assert st["state"] == "serving"
        assert st["replay"]["enabled"] is True
        assert st["replay"]["max_streams"] == 64
        assert st["replay"]["token_budget"] == 65536
        assert st["replay"]["replayed_streams"] == 0
        assert st["watchdog"]["timeout_s"] > 0
        sched.begin_drain()
        assert sched.lifecycle_stats()["state"] == "draining"
    finally:
        sched.shutdown()
