"""LoRA adapter merging (Modelfile ADAPTER): W' = W + (alpha/r)·BA applied
at load time in the transcoded layout. Equivalence is checked the
non-circular way: merging an adapter into the base must load identically to
a GGUF whose tensors were pre-modified with the same delta in GGUF layout."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from ollama_operator_tpu.gguf.lora import apply_lora
from ollama_operator_tpu.gguf.reader import GGUFFile
from ollama_operator_tpu.gguf.transcode import load_params
from ollama_operator_tpu.gguf import writer as W
from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder

from test_transcode import write_tiny_llama_gguf

ALPHA, RANK = 8.0, 4


def make_rank_r_delta(rng, out, inn):
    """A delta that IS exactly rank-RANK so the factorisation is exact."""
    B = rng.standard_normal((out, RANK)).astype(np.float32)
    A = rng.standard_normal((RANK, inn)).astype(np.float32)
    return (ALPHA / RANK) * (B @ A), A, B


def test_apply_lora_matches_premerged_gguf(tmp_path):
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    base = str(tmp_path / "base.gguf")
    write_tiny_llama_gguf(base, cfg, params)

    rng = np.random.default_rng(1)
    # targets spanning permuted (q/k) and plain (v/o/ffn) layouts + lm_head
    adapters = {}
    with GGUFFile(base) as f:
        shapes = {n: f.tensors[n].shape for n in f.tensors}
    targets = ["blk.0.attn_q.weight", "blk.1.attn_k.weight",
               "blk.0.attn_v.weight", "blk.1.attn_output.weight",
               "blk.0.ffn_up.weight", "blk.1.ffn_gate.weight",
               "blk.0.ffn_down.weight", "output.weight"]
    lora_ab = {}
    for t in targets:
        out, inn = shapes[t]
        delta, A, B = make_rank_r_delta(rng, out, inn)
        adapters[t] = delta
        lora_ab[t] = (A, B)

    # adapter GGUF with the exact A/B pairs
    ad_path = str(tmp_path / "adapter.gguf")
    w = W.GGUFWriter(ad_path)
    w.add_meta("general.architecture", "llama")
    w.add_meta("general.type", "adapter")
    w.add_meta("adapter.type", "lora")
    w.add_meta("adapter.lora.alpha", ALPHA)
    for t, (A, B) in lora_ab.items():
        w.add_tensor_f32(t + ".lora_a", A)
        w.add_tensor_f32(t + ".lora_b", B)
    w.write()

    # pre-merged GGUF: same deltas added in raw GGUF layout
    merged = str(tmp_path / "merged.gguf")
    with GGUFFile(base) as f:
        from ollama_operator_tpu.gguf import dequant as DQ
        mw = W.GGUFWriter(merged)
        for k, v in f.metadata.items():
            mw.add_meta(k, v)
        for name, t in f.tensors.items():
            arr = DQ.dequantize_tensor(f, t).astype(np.float32)
            if name in adapters:
                arr = arr + adapters[name]
            mw.add_tensor_f32(name, arr.reshape(t.shape))
        mw.write()

    with GGUFFile(base) as f:
        base_params = load_params(f, dtype=np.float32)
    got = apply_lora(base_params, cfg, ad_path)
    with GGUFFile(merged) as f:
        expect = load_params(f, dtype=np.float32)

    flat_g, _ = jax.tree_util.tree_flatten_with_path(got)
    flat_e, _ = jax.tree_util.tree_flatten_with_path(expect)
    for (pg, g), (pe, e) in zip(flat_g, flat_e):
        assert pg == pe
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=2e-4, atol=2e-4, err_msg=str(pg))


def test_apply_lora_copy_on_write(tmp_path):
    """The input tree must not be mutated (transcode-cache memmaps)."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    base = str(tmp_path / "base.gguf")
    write_tiny_llama_gguf(base, cfg, params)
    with GGUFFile(base) as f:
        base_params = load_params(f, dtype=np.float32)
    before = np.array(base_params["layers"]["wq"])

    rng = np.random.default_rng(2)
    delta, A, B = make_rank_r_delta(rng, cfg.q_dim, cfg.dim)
    ad = str(tmp_path / "a.gguf")
    w = W.GGUFWriter(ad)
    w.add_meta("general.architecture", "llama")
    w.add_meta("adapter.type", "lora")
    w.add_meta("adapter.lora.alpha", ALPHA)
    w.add_tensor_f32("blk.0.attn_q.weight.lora_a", A)
    w.add_tensor_f32("blk.0.attn_q.weight.lora_b", B)
    w.write()

    got = apply_lora(base_params, cfg, ad)
    np.testing.assert_array_equal(base_params["layers"]["wq"], before)
    assert not np.allclose(got["layers"]["wq"][0],
                           base_params["layers"]["wq"][0])
    # untouched layer shares storage semantics (equal values)
    np.testing.assert_array_equal(got["layers"]["wq"][1],
                                  base_params["layers"]["wq"][1])


def test_apply_lora_rejects_bad_targets(tmp_path):
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    base = str(tmp_path / "base.gguf")
    write_tiny_llama_gguf(base, cfg, params)
    with GGUFFile(base) as f:
        base_params = load_params(f, dtype=np.float32)

    ad = str(tmp_path / "bad.gguf")
    w = W.GGUFWriter(ad)
    w.add_meta("general.architecture", "llama")
    w.add_meta("adapter.type", "lora")
    w.add_meta("adapter.lora.alpha", ALPHA)
    w.add_tensor_f32("blk.0.ffn_gate_exps.weight.lora_a",
                     np.zeros((RANK, 8), np.float32))
    w.add_tensor_f32("blk.0.ffn_gate_exps.weight.lora_b",
                     np.zeros((8, RANK), np.float32))
    w.write()
    with pytest.raises(ValueError, match="unsupported LoRA target"):
        apply_lora(base_params, cfg, ad)

    notlora = str(tmp_path / "notlora.gguf")
    w = W.GGUFWriter(notlora)
    w.add_meta("general.architecture", "llama")
    w.add_tensor_f32("blk.0.attn_q.weight", np.zeros((4, 4), np.float32))
    w.write()
    with pytest.raises(ValueError, match="no .lora_a"):
        apply_lora(base_params, cfg, notlora)


def test_create_with_adapter_serves_merged_weights(tmp_path):
    """/api/create with ADAPTER → loaded engine params differ from base
    exactly on the adapted tensor."""
    import jax.numpy as jnp
    from ollama_operator_tpu.runtime.engine import EngineConfig
    from ollama_operator_tpu.server.app import ModelManager

    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    base = str(tmp_path / "base.gguf")
    write_tiny_llama_gguf(base, cfg, params)

    rng = np.random.default_rng(3)
    delta, A, B = make_rank_r_delta(rng, cfg.q_dim, cfg.dim)
    ad = str(tmp_path / "ad.gguf")
    w = W.GGUFWriter(ad)
    w.add_meta("general.architecture", "llama")
    w.add_meta("adapter.type", "lora")
    w.add_meta("adapter.lora.alpha", ALPHA)
    w.add_tensor_f32("blk.0.attn_q.weight.lora_a", A)
    w.add_tensor_f32("blk.0.attn_q.weight.lora_b", B)
    w.write()

    mgr = ModelManager(str(tmp_path / "store"),
                       cache_dir=str(tmp_path / "cache"),
                       ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                         cache_dtype=jnp.float32,
                                         min_prefill_bucket=16),
                       engine_dtype="float32")
    mgr.create("tinybase", f"FROM {base}")
    mgr.create("tinylora", f"FROM tinybase\nADAPTER {ad}")
    show = mgr.show("tinylora")
    assert "ADAPTER" in show["modelfile"]

    lm_base = mgr.load("tinybase")
    wq_base = np.array(lm_base.engine.params["layers"]["wq"])
    lm_lora = mgr.load("tinylora")
    wq_lora = np.array(lm_lora.engine.params["layers"]["wq"])
    assert not np.allclose(wq_base[0], wq_lora[0])
    np.testing.assert_array_equal(wq_base[1], wq_lora[1])
    r = lm_lora.generate("hello", options={"num_predict": 3,
                                           "temperature": 0.0})
    assert r.generated_tokens >= 1
    lm_lora.unload()
