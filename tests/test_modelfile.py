from ollama_operator_tpu.server.modelfile import parse_modelfile


def test_basic_modelfile():
    mf = parse_modelfile("""
# a comment
FROM llama2
PARAMETER temperature 0.7
PARAMETER top_k 50
PARAMETER stop "<|im_end|>"
PARAMETER stop "</s>"
SYSTEM You are helpful.
""")
    assert mf.from_ == "llama2"
    assert mf.parameters["temperature"] == 0.7
    assert mf.parameters["top_k"] == 50
    assert mf.parameters["stop"] == ["<|im_end|>", "</s>"]
    assert mf.system == "You are helpful."


def test_triple_quoted_template():
    mf = parse_modelfile('FROM m\nTEMPLATE """{{ .System }}\n'
                         '{{ .Prompt }}"""\n')
    assert mf.template == "{{ .System }}\n{{ .Prompt }}"


def test_single_line_triple_quote():
    mf = parse_modelfile('FROM m\nSYSTEM """all on one line"""')
    assert mf.system == "all on one line"


def test_message_commands():
    mf = parse_modelfile('FROM m\nMESSAGE user hello\nMESSAGE assistant hi')
    assert mf.messages == [("user", "hello"), ("assistant", "hi")]


def test_render_roundtrip():
    mf = parse_modelfile("FROM base\nPARAMETER temperature 0.1\n"
                         'SYSTEM """s"""')
    text = mf.render()
    mf2 = parse_modelfile(text)
    assert mf2.from_ == "base"
    assert mf2.parameters["temperature"] == 0.1
    assert mf2.system == "s"
