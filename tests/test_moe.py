"""Mixture-of-experts (mixtral family): routing correctness vs a numpy
reference, impl parity (einsum vs scan), prefill/decode equivalence,
expert-parallel engine on a dp×ep×tp CPU mesh, and GGUF transcode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.gguf import transcode as TC
from ollama_operator_tpu.gguf.reader import GGUFFile
from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.parallel import MeshPlan, make_mesh
from ollama_operator_tpu.parallel.sharding import params_pspec_tree
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

from test_transcode import write_tiny_llama_gguf

rng = np.random.default_rng(11)
F32 = jnp.float32


def tiny_moe(**kw):
    base = cfglib.PRESETS["tiny-moe"]
    return cfglib.ModelConfig(**{**base.__dict__, **kw}).validate()


def numpy_moe_mlp(cfg, lp, x):
    """Straightforward per-token loop reference (mixtral semantics:
    full-softmax over router logits, top-k renormalised)."""
    B, T, D = x.shape
    E, k = cfg.n_experts, cfg.n_experts_used
    out = np.zeros((B, T, D), np.float32)

    def silu(a):
        return a / (1.0 + np.exp(-a))

    for b in range(B):
        for t in range(T):
            xv = np.asarray(x[b, t], np.float32)
            logits = xv @ np.asarray(lp["router"], np.float32)
            probs = np.exp(logits - logits.max())
            probs /= probs.sum()
            top = np.argsort(-probs)[:k]
            wts = probs[top] / probs[top].sum()
            for w, e in zip(wts, top):
                wg = np.asarray(lp["we_gate"][e], np.float32)
                wu = np.asarray(lp["we_up"][e], np.float32)
                wd = np.asarray(lp["we_down"][e], np.float32)
                h = silu(xv @ wg) * (xv @ wu)
                out[b, t] += w * (h @ wd)
    return out


def layer0(params):
    """Slice layer 0's MoE leaves out of the stacked tree."""
    lp = params["layers"]
    return {k: lp[k][0] for k in ("router", "we_gate", "we_up", "we_down")}


@pytest.mark.parametrize("impl", ["einsum", "scan"])
def test_moe_mlp_matches_numpy(impl):
    cfg = tiny_moe(moe_impl=impl)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    lp = layer0(params)
    x = jnp.asarray(rng.standard_normal((2, 5, cfg.dim)), F32)
    got = decoder._moe_mlp(cfg, lp, x)
    want = numpy_moe_mlp(cfg, lp, x)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_moe_impl_parity():
    """einsum and scan paths must agree bit-for-bit-ish."""
    cfg_e = tiny_moe(moe_impl="einsum")
    cfg_s = tiny_moe(moe_impl="scan")
    params = decoder.init_params(cfg_e, jax.random.PRNGKey(1), dtype=F32)
    lp = layer0(params)
    x = jnp.asarray(rng.standard_normal((1, 300, cfg_e.dim)), F32)
    a = decoder._moe_mlp(cfg_e, lp, x)
    b = decoder._moe_mlp(cfg_s, lp, x)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-5)


def test_moe_prefill_decode_equivalence():
    cfg = tiny_moe()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    B, T, split = 2, 12, 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                cfg.vocab_size)
    ref_logits, _, _ = decoder.prefill_chunk(params, cfg, tokens)

    logits_p, ks, vs = decoder.prefill_chunk(params, cfg, tokens[:, :split])
    S = 32
    shape = (cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim)
    k_cache = jnp.zeros(shape, F32).at[:, :, :, :split].set(ks)
    v_cache = jnp.zeros(shape, F32).at[:, :, :, :split].set(vs)
    lengths = jnp.full((B,), split, jnp.int32)
    np.testing.assert_allclose(np.asarray(logits_p),
                               np.asarray(ref_logits[:, :split]),
                               rtol=2e-4, atol=2e-4)
    for i in range(split, T):
        logits_d, k_cache, v_cache = decoder.forward_with_cache(
            params, cfg, tokens[:, i:i + 1], k_cache, v_cache, lengths)
        lengths = lengths + 1
        np.testing.assert_allclose(np.asarray(logits_d[:, 0]),
                                   np.asarray(ref_logits[:, i]),
                                   rtol=3e-4, atol=3e-4)


def test_moe_pspec_tree_has_expert_axes():
    cfg = tiny_moe()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshPlan(dp=2, ep=2, tp=2))
    tree = params_pspec_tree(params, cfg, mesh)
    assert tree["layers"]["we_gate"] == jax.sharding.PartitionSpec(
        None, "ep", None, "tp")
    assert tree["layers"]["we_down"] == jax.sharding.PartitionSpec(
        None, "ep", "tp", None)
    # 3 experts don't divide ep=2 → replicate expert axis
    cfg3 = tiny_moe(n_experts=3, n_experts_used=2)
    p3 = decoder.init_params(cfg3, jax.random.PRNGKey(0))
    tree3 = params_pspec_tree(p3, cfg3, mesh)
    assert tree3["layers"]["we_gate"] == jax.sharding.PartitionSpec(
        None, None, None, "tp")


def test_moe_engine_expert_parallel_matches_single_device():
    """Greedy decode through the Engine on a dp2×ep2×tp2 mesh must produce
    the same tokens as the single-device engine."""
    cfg = tiny_moe()
    params = decoder.init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_seq_len=64, min_prefill_bucket=8,
                        cache_dtype=jnp.float32)
    opts = SlotOptions(temperature=0.0)
    prompt = np.asarray(rng.integers(1, cfg.vocab_size, 13), np.int32)

    eng1 = Engine(cfg, params, mesh=None, ecfg=ecfg)
    t1 = [eng1.admit(0, prompt, opts)]
    for _ in range(6):
        t1.append(int(eng1.decode()[0]))

    mesh = make_mesh(MeshPlan(dp=2, ep=2, tp=2))
    eng8 = Engine(cfg, params, mesh=mesh, ecfg=ecfg)
    t8 = [eng8.admit(0, prompt, opts)]
    for _ in range(6):
        t8.append(int(eng8.decode()[0]))

    assert t1 == t8


@pytest.mark.parametrize("merged", [True, False])
def test_moe_gguf_roundtrip_logits_match(tmp_path, merged):
    cfg = tiny_moe()
    params = decoder.init_params(cfg, jax.random.PRNGKey(3), dtype=F32)
    path = str(tmp_path / "moe.gguf")
    write_tiny_llama_gguf(path, cfg, params, moe_merged=merged)

    with GGUFFile(path) as f:
        cfg2 = TC.config_from_gguf(f)
        assert cfg2.n_experts == cfg.n_experts
        assert cfg2.n_experts_used == cfg.n_experts_used
        params2 = TC.load_params(f, cfg2, dtype=np.float32)

    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 9)))
    ref, _, _ = decoder.prefill_chunk(params, cfg, tokens)
    p2 = jax.tree_util.tree_map(jnp.asarray, params2)
    out, _, _ = decoder.prefill_chunk(p2, cfg2, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_mixtral_preset_param_count():
    cfg = cfglib.get_config("mixtral")
    # 8x7B ≈ 46.7B params
    assert 45e9 < cfg.n_params < 49e9
