"""Native (C++) dequant kernels must match the numpy reference bit-for-bit
on finite values."""

import numpy as np
import pytest

from ollama_operator_tpu.gguf import dequant as DQ
from ollama_operator_tpu.gguf import native as N
from ollama_operator_tpu.gguf import reader as R

rng = np.random.default_rng(11)

pytestmark = pytest.mark.skipif(N.load() is None,
                                reason="no C++ toolchain available")


@pytest.mark.parametrize("ggml_type,numpy_fn,block_bytes", [
    (R.GGML_Q4_0, DQ.dq_q4_0, 18),
    (R.GGML_Q8_0, DQ.dq_q8_0, 34),
    (R.GGML_Q4_K, DQ.dq_q4_k, 144),
    (R.GGML_Q5_K, DQ.dq_q5_k, 176),
    (R.GGML_Q6_K, DQ.dq_q6_k, 210),
])
def test_native_matches_numpy(ggml_type, numpy_fn, block_bytes):
    raw = rng.integers(0, 256, size=8 * block_bytes, dtype=np.uint8)
    ref = numpy_fn(raw)
    out = N.native_dequantize(raw, ggml_type)
    assert out is not None
    mask = np.isfinite(ref)
    np.testing.assert_array_equal(out[mask], ref[mask])
    assert (np.isfinite(out) == mask).all()


def test_native_f16():
    vals = rng.standard_normal(256).astype(np.float16)
    raw = vals.view(np.uint8)
    out = N.native_dequantize(np.ascontiguousarray(raw), R.GGML_F16)
    np.testing.assert_array_equal(out, vals.astype(np.float32))


def test_native_bf16_roundtrip():
    lib = N.load()
    x = rng.standard_normal(1024).astype(np.float32)
    out = np.empty(1024, np.uint16)
    lib.f32_to_bf16(x, out, 1024)
    import ml_dtypes
    ref = x.astype(ml_dtypes.bfloat16).view(np.uint16)
    np.testing.assert_array_equal(out, ref)


def test_install_speeds_up_dispatch():
    assert N.install()
    raw = rng.integers(0, 256, size=4 * 144, dtype=np.uint8)
    y = DQ.dequantize(raw, R.GGML_Q4_K, (4, 256))
    assert y.shape == (4, 256)
