"""Request-lifecycle tracing, the flight recorder, and metrics hygiene.

Covers runtime/trace.py (span timelines, the crash flight recorder),
the scheduler's event threading, the latency histograms, and the
strict Prometheus text-format contract /metrics must satisfy (the same
validator the CI metrics-lint step runs over a live scrape)."""

import io
import json
import re
import time

import numpy as np
import pytest

from ollama_operator_tpu.runtime import trace as trace_mod
from ollama_operator_tpu.runtime.faults import FAULTS, InjectedFault
from ollama_operator_tpu.runtime.trace import (FLIGHT, NULL_TRACE, TRACER,
                                               FlightRecorder, RequestTrace,
                                               Tracer)
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS
from ollama_operator_tpu.server.metrics import Metrics

from test_scheduler import GREEDY, make_stack


# -- RequestTrace ------------------------------------------------------

def test_request_trace_events_and_event_at():
    tr = RequestTrace("42")
    tr.event("queued", n_prompt=3)
    t_launch = time.perf_counter()
    tr.event("admitted", slot=0)
    tr.event_at(t_launch, "dispatch", kind="decode")
    d = tr.to_dict()
    assert d["id"] == "42"
    names = [e["ev"] for e in d["events"]]
    assert names == ["queued", "admitted", "dispatch"]
    assert d["events"][0]["n_prompt"] == 3
    # event_at back-dates: the dispatch launch precedes the admitted stamp
    assert d["events"][2]["t_ms"] <= d["events"][1]["t_ms"]
    assert all(e["t_ms"] >= 0 for e in d["events"])


def test_request_trace_timings_summary():
    tr = RequestTrace("7")
    tr.event("queued")
    tr.event("admitted")
    tr.event("dispatch")
    tr.event("dispatch")
    tm = tr.timings()
    spans = {s["ev"]: s for s in tm["spans"]}
    assert spans["dispatch"]["n"] == 2
    assert spans["dispatch"]["first_ms"] <= spans["dispatch"]["last_ms"]
    assert tm["queue_wait_ms"] >= 0


def test_null_trace_is_inert():
    NULL_TRACE.event("x", a=1)
    NULL_TRACE.event_at(0.0, "y")
    NULL_TRACE.set_identity("high", "acme")
    assert NULL_TRACE.to_dict()["events"] == []
    assert NULL_TRACE.timings() == {"spans": []}
    assert NULL_TRACE.cls is None and NULL_TRACE.tenant is None


def test_request_trace_identity_labels():
    tr = RequestTrace("9")
    d = tr.to_dict()
    assert "class" not in d and "tenant" not in d   # unset → omitted
    tr.set_identity("high", "acme")
    d = tr.to_dict()
    assert d["class"] == "high" and d["tenant"] == "acme"
    # falsy args never clobber an identity already set
    tr.set_identity(None, None)
    assert tr.cls == "high" and tr.tenant == "acme"


# -- Tracer registry ---------------------------------------------------

def test_tracer_bounded_registry_evicts_oldest():
    t = Tracer(keep=3)
    for i in range(5):
        t.begin(i)
    assert t.ids() == ["2", "3", "4"]
    assert t.get(1) is None
    assert t.get("4").rid == "4"


def test_tracer_disabled_returns_null(monkeypatch):
    monkeypatch.setattr(trace_mod, "TRACE_ENABLED", False)
    t = Tracer(keep=3)
    tr = t.begin(99)
    assert tr is NULL_TRACE
    assert t.ids() == []        # nothing registered when disabled


# -- FlightRecorder ----------------------------------------------------

def test_flight_recorder_ring_bounds_and_seq():
    fr = FlightRecorder(maxlen=16)
    for i in range(40):
        fr.record("tick", i=i)
    evs = fr.snapshot()
    assert len(evs) == 16                    # ring keeps only the tail
    assert fr.seq == 40                      # ...but the seq keeps counting
    assert [e["i"] for e in evs] == list(range(24, 40))
    assert [e["seq"] for e in evs] == list(range(25, 41))


def test_flight_recorder_dump_format():
    fr = FlightRecorder(maxlen=16)
    fr.record("admit", rid=1, slot=0)
    fr.record("restart", n=1)
    out = io.StringIO()
    n = fr.dump("unit test", stream=out)
    assert n == 2 and fr.dumps == 1
    lines = out.getvalue().splitlines()
    assert lines[0] == "--- flight recorder dump: unit test (2 events) ---"
    assert lines[-1] == "--- end flight recorder dump: unit test ---"
    evs = [json.loads(ln) for ln in lines[1:-1]]
    assert [e["kind"] for e in evs] == ["admit", "restart"]
    assert all("t_unix" in e and "seq" in e for e in evs)
    # last= trims to the newest events
    out2 = io.StringIO()
    assert fr.dump("tail", stream=out2, last=1) == 1
    assert json.loads(out2.getvalue().splitlines()[1])["kind"] == "restart"


def test_fault_injection_lands_in_flight_recorder():
    seq0 = FLIGHT.seq
    FAULTS.arm("unit.point", "fail:once")
    with pytest.raises(InjectedFault):
        FAULTS.check("unit.point")
    evs = [e for e in FLIGHT.snapshot() if e["seq"] > seq0]
    faults = [e for e in evs if e["kind"] == "fault_injected"]
    assert faults and faults[0]["point"] == "unit.point"
    assert faults[0]["spec"] == "fail:once"


# -- scheduler threading -----------------------------------------------

def test_scheduler_traces_request_lifecycle():
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        r = sched.submit(np.array([1, 2, 3], np.int32), GREEDY,
                         max_tokens=5)
        assert len(list(r.tokens())) == 5
        tr = TRACER.get(r.id)
        assert tr is not None
        names = [n for _, n, _ in tr.events]
        for must in ("queued", "admitted", "first_token", "finish"):
            assert must in names, f"missing {must!r} in {names}"
        assert any(n.startswith("prefill") for n in names)
        assert any(n == "dispatch" for n in names)
        # timeline is summarisable for the opt-in timings block
        tm = tr.timings()
        assert tm["queue_wait_ms"] >= 0
        assert {s["ev"] for s in tm["spans"]} >= {"queued", "finish"}
    finally:
        sched.shutdown()


def test_scheduler_threads_identity_into_trace():
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        r = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=3,
                         priority="high", tenant="acme")
        list(r.tokens())
        d = TRACER.get(r.id).to_dict()
        assert d["class"] == "high" and d["tenant"] == "acme"
    finally:
        sched.shutdown()


def test_displacement_records_flight_event():
    """Satellite 2: queue-full displacement leaves a dedicated
    'displaced' event carrying both sides' class/tenant, distinct from
    the victim's own shed."""
    from test_stall_free import manual
    seq0 = FLIGHT.seq
    sched = manual(make_stack(slots=1)[3])
    sched._admission.max_queue = 2
    try:
        sched.submit(np.array([1], np.int32), GREEDY, max_tokens=8,
                     priority="normal")
        victim = sched.submit(np.array([2], np.int32), GREEDY,
                              max_tokens=8, priority="best_effort",
                              tenant="acme")
        high = sched.submit(np.array([3], np.int32), GREEDY, max_tokens=8,
                            priority="high")
        evs = [e for e in FLIGHT.snapshot()
               if e["seq"] > seq0 and e["kind"] == "displaced"]
        assert evs, "no displaced event recorded"
        assert evs[0]["rid"] == victim.id
        assert evs[0]["cls"] == "best_effort"
        assert evs[0]["tenant"] == "acme"
        assert evs[0]["by"] == high.id
        assert evs[0]["by_cls"] == "high"
    finally:
        sched.shutdown()


def test_scheduler_records_admit_flight_events():
    seq0 = FLIGHT.seq
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        r = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=3)
        list(r.tokens())
        admits = [e for e in FLIGHT.snapshot()
                  if e["seq"] > seq0 and e["kind"] == "admit"]
        assert any(e["rid"] == r.id for e in admits)
    finally:
        sched.shutdown()


def test_scheduler_observes_latency_histograms():
    q0 = _hist_count("tpu_model_queue_wait_seconds")
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        r = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=4)
        list(r.tokens())
    finally:
        sched.shutdown()
    assert _hist_count("tpu_model_queue_wait_seconds") > q0
    text = METRICS.render()
    assert 'tpu_model_dispatch_seconds_bucket{kind="decode"' in text \
        or 'tpu_model_dispatch_seconds_bucket{kind="spec"' in text
    assert re.search(r'tpu_model_dispatch_seconds_bucket\{kind="(admit|'
                     r'extend)"', text)


def _hist_count(name, labels=""):
    h = METRICS._hists.get((name, labels))
    return h.n if h is not None else 0


@pytest.mark.chaos
def test_supervised_restart_dumps_flight_recorder(capsys, monkeypatch):
    """ISSUE 7 acceptance: the chaos drill's supervised restart dumps a
    flight-recorder post-mortem — >= 10 structured events including the
    injected fault and the restart itself."""
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    dumps0 = FLIGHT.dumps
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    try:
        # a little pre-fault traffic so the ring has history to dump
        for i in range(3):
            r = sched.submit(np.array([i + 1, i + 2], np.int32), GREEDY,
                             max_tokens=3)
            list(r.tokens())
        seq_fault = FLIGHT.seq
        FAULTS.arm("engine.step", "fail:once")
        r1 = sched.submit(np.array([9, 9], np.int32), GREEDY, max_tokens=4)
        with pytest.raises(RuntimeError, match="injected fault"):
            list(r1.tokens())
        deadline = time.monotonic() + 5
        while FLIGHT.dumps == dumps0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert FLIGHT.dumps == dumps0 + 1
        kinds = [e["kind"] for e in FLIGHT.snapshot()
                 if e["seq"] > seq_fault]
        assert "fault_injected" in kinds
        assert "engine_failure" in kinds
        assert "restart" in kinds
        assert len(FLIGHT.snapshot()) >= 10
        err = capsys.readouterr().err
        assert "flight recorder dump: supervised restart #" in err
    finally:
        sched.shutdown()


# -- metrics hygiene ---------------------------------------------------

def test_gauge_errors_counted_not_swallowed():
    m = Metrics()

    def boom():
        raise RuntimeError("dead weakref")

    m.gauge_fn("good_gauge", lambda: 7.0)
    m.gauge_fn("bad_gauge", boom)
    text = m.render()
    assert "good_gauge 7.0" in text
    assert "bad_gauge" not in text
    # the failure is counted, and visible in the SAME scrape
    assert "tpu_model_metrics_gauge_errors_total 1.0" in text
    assert "tpu_model_metrics_gauge_errors_total 2.0" in m.render()


def test_preseeded_counters_present_when_idle():
    text = METRICS.render()
    for name in ("tpu_model_preemptions_total",
                 "tpu_model_requests_total",
                 "tpu_model_generated_tokens_total",
                 "tpu_model_prompt_tokens_total",
                 "tpu_model_stream_frames_total",
                 "tpu_model_metrics_gauge_errors_total"):
        assert re.search(rf"^{name} [0-9.]+$", text, re.M), \
            f"{name} absent from an idle scrape"


def test_shed_counter_preseeds_full_label_matrix():
    """ISSUE 8: every {class,cause} combination of tpu_model_shed_total
    must exist at 0 before the first shed — a PromQL rate() over a
    series that appears mid-incident reads as a counter reset. Same for
    the per-tenant throttle/token series (default bucket)."""
    from ollama_operator_tpu.runtime.admission import (PRIORITIES,
                                                       SHED_CAUSES,
                                                       shed_labels)
    text = METRICS.render()
    for p in PRIORITIES:
        for c in SHED_CAUSES:
            series = f"tpu_model_shed_total{shed_labels(p, c)}"
            assert re.search(rf"^{re.escape(series)} [0-9.]+$", text,
                             re.M), f"{series} not pre-seeded"
    for series in (
            'tpu_model_tenant_throttles_total'
            '{class="best_effort",tenant="default"}',
            'tpu_model_tenant_decode_tokens_total{tenant="default"}'):
        assert re.search(rf"^{re.escape(series)} [0-9.]+$", text, re.M), \
            f"{series} not pre-seeded"


def test_utilization_metric_families_preseeded():
    """PR 10: every utilization/goodput series must exist at 0 on an
    idle scrape — rate() over a series that first appears mid-serving
    reads as a counter reset (same discipline as the shed matrix)."""
    text = METRICS.render()
    series = ([f'tpu_model_recompiles_total{{kind="{k}"}}'
               for k in ("decode", "admit", "admit_many", "extend", "spec")]
              + [f'tpu_model_useful_tokens_total{{kind="{k}"}}'
                 for k in ("decode", "prefill", "spec")]
              + [f'tpu_model_padded_tokens_total{{kind="{k}"}}'
                 for k in ("decode", "prefill", "spec")]
              + [f'tpu_model_breakdown_seconds_total{{phase="{p}"}}'
                 for p in ("dispatch_wait", "host", "idle")])
    for s in series:
        assert re.search(rf"^{re.escape(s)} [0-9.]+$", text, re.M), \
            f"{s} not pre-seeded"
    assert re.search(r"^tpu_model_model_flops_total [0-9.eE+]+$", text,
                     re.M), "tpu_model_model_flops_total not pre-seeded"


def test_utilization_series_pass_strict_validator():
    from ollama_operator_tpu.models.config import PRESETS
    from ollama_operator_tpu.runtime.accounting import UtilizationAccounting
    acct = UtilizationAccounting(PRESETS["tiny"], peak_flops=1e12,
                                 device_kind="unit")
    acct.on_decode(0.01, ctxs=[4, 6], n_steps=2, capacity=4)
    acct.on_prefill(0.01, 0, 5, 16)
    acct.on_spec(0.01, ctxs=[8], k=2, emitted=1.0, capacity=1)
    acct.on_wait(0.005)
    acct.on_idle(0.005)
    validate_prometheus_text(METRICS.render())


def test_admission_label_sets_pass_strict_validator():
    """Sheds, per-class queue-wait observations, and per-tenant series
    must render as parseable, HELP/TYPE-covered samples — label sets
    with {class,tenant,cause} go through the same strict contract as
    everything else."""
    from ollama_operator_tpu.runtime.admission import shed_labels
    METRICS.inc("tpu_model_shed_total",
                labels=shed_labels("best_effort", "queue_full"))
    METRICS.inc("tpu_model_tenant_throttles_total",
                labels='{class="best_effort",tenant="unit-t"}')
    METRICS.inc("tpu_model_tenant_decode_tokens_total", 5.0,
                '{tenant="unit-t"}')
    METRICS.observe("tpu_model_class_queue_wait_seconds", 0.002,
                    '{class="high"}')
    text = METRICS.render()
    validate_prometheus_text(text)
    assert 'tpu_model_class_queue_wait_seconds_bucket{class="high"' in text


# -- strict Prometheus text-format validator ---------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? (NaN|[+-]?Inf|[-+0-9.eE]+)$")


def _strip_le(labels):
    """Histogram group key: the label set minus the per-bucket le."""
    if not labels:
        return ""
    parts = [p for p in labels[1:-1].split(",")
             if p and not p.startswith("le=")]
    return "{" + ",".join(parts) + "}" if parts else ""


def validate_prometheus_text(text):
    """Strict structural check of a text-format exposition: HELP and TYPE
    on every series, no duplicate headers, parseable samples, cumulative
    monotone histogram buckets with consistent _count/_sum. Shared with
    test_server (live /metrics scrape) and the CI metrics-lint step."""
    types, helps, samples = {}, {}, []
    assert text.endswith("\n"), "exposition must end with a newline"
    for ln in text.rstrip("\n").splitlines():
        if ln.startswith("# HELP "):
            name = ln.split()[2]
            assert name not in helps, f"duplicate HELP for {name}"
            helps[name] = ln
        elif ln.startswith("# TYPE "):
            parts = ln.split()
            assert len(parts) == 4, f"malformed TYPE line: {ln!r}"
            name, typ = parts[2], parts[3]
            assert name not in types, f"duplicate TYPE for {name}"
            assert typ in ("counter", "gauge", "histogram"), ln
            types[name] = typ
        else:
            m = _SAMPLE_RE.match(ln)
            assert m, f"unparseable sample line: {ln!r}"
            samples.append((m.group(1), m.group(2) or "",
                            float(m.group(3))))

    def base_of(name):
        for suf in ("_bucket", "_sum", "_count"):
            root = name[:-len(suf)] if name.endswith(suf) else None
            if root and types.get(root) == "histogram":
                return root
        return name

    hist_groups = {}
    for name, labels, val in samples:
        base = base_of(name)
        assert base in types, f"sample {name} has no TYPE header"
        assert base in helps, \
            f"series {base} lacks HELP (add a describe() call)"
        if types[base] == "histogram":
            key = (base, _strip_le(labels))
            g = hist_groups.setdefault(key,
                                       {"buckets": [], "sum": None,
                                        "count": None})
            if name.endswith("_bucket"):
                le = re.search(r'le="([^"]*)"', labels).group(1)
                g["buckets"].append((float("inf") if le == "+Inf"
                                     else float(le), val))
            elif name.endswith("_sum"):
                g["sum"] = val
            elif name.endswith("_count"):
                g["count"] = val
        elif types[base] == "counter":
            assert val >= 0, f"counter {name} is negative: {val}"
    for (base, _), g in hist_groups.items():
        assert g["sum"] is not None and g["count"] is not None, \
            f"histogram {base} missing _sum/_count"
        les = [le for le, _ in g["buckets"]]
        counts = [c for _, c in g["buckets"]]
        assert les == sorted(les), f"{base} buckets out of order"
        assert les and les[-1] == float("inf"), f"{base} lacks +Inf bucket"
        assert counts == sorted(counts), \
            f"{base} cumulative counts not monotone: {counts}"
        assert counts[-1] == g["count"], \
            f"{base} +Inf bucket {counts[-1]} != _count {g['count']}"
    assert samples, "empty exposition"
    return len(samples)


def test_global_metrics_pass_strict_validator():
    # exercise at least one histogram + counter first so the validator
    # sees every shape
    METRICS.observe("tpu_model_queue_wait_seconds", 0.001)
    assert validate_prometheus_text(METRICS.render()) > 10


def test_validator_rejects_bad_expositions():
    good = ("# HELP x_total ok\n# TYPE x_total counter\nx_total 1.0\n")
    validate_prometheus_text(good)
    with pytest.raises(AssertionError, match="lacks HELP"):
        validate_prometheus_text("# TYPE y counter\ny 1.0\n")
    with pytest.raises(AssertionError, match="no TYPE"):
        validate_prometheus_text("# HELP y ok\ny 1.0\n")
    with pytest.raises(AssertionError, match="duplicate TYPE"):
        validate_prometheus_text("# HELP y ok\n# TYPE y counter\n"
                                 "# TYPE y counter\ny 1.0\n")
    bad_hist = ("# HELP h ok\n# TYPE h histogram\n"
                'h_bucket{le="0.1"} 5\nh_bucket{le="1.0"} 3\n'
                'h_bucket{le="+Inf"} 3\nh_sum 1.0\nh_count 3\n')
    with pytest.raises(AssertionError, match="not monotone"):
        validate_prometheus_text(bad_hist)
