"""Manager + HTTP-client tests.

Tier (b) of SURVEY.md §4's pyramid: the stdlib KubeClient speaks to the
fake apiserver over REAL HTTP (wire format, error mapping, chunked watch),
and the Manager's watch→queue→reconcile loop drives a Model to Available
end-to-end, with a kubelet-player thread flipping readiness — the closest
analog to envtest's "real API, fake kubelet" the reference relies on.
"""

import threading
import time

import pytest

from ollama_operator_tpu.operator import workload
from ollama_operator_tpu.operator.client import Conflict, KubeClient, NotFound
from ollama_operator_tpu.operator.manager import (LeaderElector, Manager,
                                                  WorkQueue)
from ollama_operator_tpu.operator.reconciler import is_condition_true
from ollama_operator_tpu.operator.types import API_VERSION, KIND

from fake_kube import FakeKube, serve_http


@pytest.fixture()
def fake():
    return FakeKube()


@pytest.fixture()
def http_client(fake):
    httpd = serve_http(fake)
    addr = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield KubeClient(addr, timeout=5)
    httpd.shutdown()


def model_obj(name="phi", **spec):
    spec.setdefault("image", "phi")
    spec.setdefault("runtime", "cpu")
    return {"apiVersion": API_VERSION, "kind": KIND,
            "metadata": {"name": name, "namespace": "default"},
            "spec": spec}


class TestHttpClient:
    def test_crud_roundtrip(self, http_client):
        created = http_client.create(model_obj())
        assert created["metadata"]["resourceVersion"]
        got = http_client.get(API_VERSION, KIND, "default", "phi")
        assert got["spec"]["image"] == "phi"
        got["spec"]["replicas"] = 2
        updated = http_client.update(got)
        assert updated["spec"]["replicas"] == 2
        assert http_client.get(API_VERSION, KIND, "default", "ghost") is None
        http_client.delete(API_VERSION, KIND, "default", "phi")
        assert http_client.get(API_VERSION, KIND, "default", "phi") is None

    def test_status_subresource_is_separate(self, http_client):
        http_client.create(model_obj())
        m = http_client.get(API_VERSION, KIND, "default", "phi")
        m["status"] = {"replicas": 3}
        http_client.update_status(m)
        # spec update must not clobber status, and vice versa
        m = http_client.get(API_VERSION, KIND, "default", "phi")
        m["spec"]["replicas"] = 5
        http_client.update(m)
        m = http_client.get(API_VERSION, KIND, "default", "phi")
        assert m["status"]["replicas"] == 3 and m["spec"]["replicas"] == 5

    def test_conflict_and_duplicate_create(self, http_client):
        http_client.create(model_obj())
        with pytest.raises(Conflict):
            http_client.create(model_obj())
        stale = http_client.get(API_VERSION, KIND, "default", "phi")
        fresh = http_client.get(API_VERSION, KIND, "default", "phi")
        fresh["spec"]["replicas"] = 2
        http_client.update(fresh)
        stale["spec"]["replicas"] = 9
        with pytest.raises(Conflict):
            http_client.update(stale)

    def test_list_with_label_selector(self, http_client, fake):
        a = model_obj("a")
        a["metadata"]["labels"] = {"tier": "prod"}
        http_client.create(a)
        http_client.create(model_obj("b"))
        items = http_client.list(API_VERSION, KIND, "default",
                                 label_selector="tier=prod")
        assert [i["metadata"]["name"] for i in items] == ["a"]

    def test_watch_streams_events(self, http_client, fake):
        stop = threading.Event()
        seen = []

        def consume():
            for evt in http_client.watch(API_VERSION, KIND, "default",
                                         stop=stop):
                seen.append((evt["type"],
                             evt["object"]["metadata"]["name"]))
                if len(seen) >= 2:
                    return

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        time.sleep(0.3)  # watcher registers
        fake.create(model_obj("w1"))
        fake.create(model_obj("w2"))
        t.join(timeout=5)
        stop.set()
        assert ("ADDED", "w1") in seen and ("ADDED", "w2") in seen


class TestWorkQueue:
    def test_dedupe(self):
        q = WorkQueue()
        q.add(("ns", "a"))
        q.add(("ns", "a"))
        q.add(("ns", "b"))
        assert q.get(timeout=1) == ("ns", "a")
        assert q.get(timeout=1) == ("ns", "b")
        assert q.get(timeout=0.1) is None

    def test_delay_ordering_and_supersede(self):
        q = WorkQueue()
        q.add(("ns", "slow"), delay=5.0)
        q.add(("ns", "fast"), delay=0.0)
        assert q.get(timeout=1) == ("ns", "fast")
        q.add(("ns", "slow"), delay=0.0)  # sooner wins
        assert q.get(timeout=1) == ("ns", "slow")
        assert q.get(timeout=0.1) is None


class TestLeaderElection:
    def test_single_holder(self, fake):
        a = LeaderElector(fake, "default", identity="a", lease_seconds=2)
        b = LeaderElector(fake, "default", identity="b", lease_seconds=2)
        assert a._try_acquire() is True
        assert b._try_acquire() is False
        lease = fake.get("coordination.k8s.io/v1", "Lease", "default",
                         a.name)
        assert lease["spec"]["holderIdentity"] == "a"

    def test_takeover_after_expiry(self, fake):
        a = LeaderElector(fake, "default", identity="a", lease_seconds=1)
        assert a._try_acquire()
        lease = fake.get("coordination.k8s.io/v1", "Lease", "default",
                         a.name)
        lease["spec"]["renewTime"] = "2000-01-01T00:00:00.0000000Z"
        fake.update(lease)
        b = LeaderElector(fake, "default", identity="b", lease_seconds=1)
        assert b._try_acquire() is True


def play_kubelet(fake, stop):
    """Flip readiness of everything the reconciler creates."""
    while not stop.is_set():
        for sts in fake.list("apps/v1", "StatefulSet", "default"):
            n = sts["spec"].get("replicas", 1)
            if (sts.get("status") or {}).get("readyReplicas") != n:
                fake.set_status("apps/v1", "StatefulSet", "default",
                                sts["metadata"]["name"],
                                {"readyReplicas": n, "replicas": n})
        for dep in fake.list("apps/v1", "Deployment", "default"):
            n = dep["spec"].get("replicas", 1)
            if (dep.get("status") or {}).get("readyReplicas") != n:
                fake.set_status("apps/v1", "Deployment", "default",
                                dep["metadata"]["name"],
                                {"replicas": n, "readyReplicas": n,
                                 "availableReplicas": n})
        for svc in fake.list("v1", "Service", "default"):
            if not svc["spec"].get("clusterIP"):
                svc["spec"]["clusterIP"] = "10.0.0.9"
                try:
                    fake.update(svc)
                except Conflict:
                    pass
        stop.wait(0.05)


class TestManagerEndToEnd:
    def test_watch_to_available(self, fake):
        mgr = Manager(fake, namespace="default", server_image="img:t")
        # shrink poll delays so the test runs fast
        import ollama_operator_tpu.operator.reconciler as r
        stop = threading.Event()
        kubelet = threading.Thread(target=play_kubelet, args=(fake, stop),
                                   daemon=True)
        kubelet.start()
        mgr.start(workers=2, serve_health=False)
        try:
            fake.create(model_obj("e2e"))
            deadline = time.time() + 30
            while time.time() < deadline:
                m = fake.get(API_VERSION, KIND, "default", "e2e")
                if m and is_condition_true(m, "Available"):
                    break
                time.sleep(0.1)
            else:
                raise AssertionError("model never became Available")
            dep = fake.get("apps/v1", "Deployment", "default",
                           "ollama-model-e2e")
            assert dep is not None
            assert fake.get("v1", "Service", "default",
                            "ollama-model-e2e") is not None
        finally:
            stop.set()
            mgr.stop()

    def test_workload_drift_heals(self, fake):
        mgr = Manager(fake, namespace="default", server_image="img:t")
        stop = threading.Event()
        kubelet = threading.Thread(target=play_kubelet, args=(fake, stop),
                                   daemon=True)
        kubelet.start()
        mgr.start(workers=2, serve_health=False)
        try:
            fake.create(model_obj("drift"))
            deadline = time.time() + 30
            while time.time() < deadline:
                m = fake.get(API_VERSION, KIND, "default", "drift")
                if m and is_condition_true(m, "Available"):
                    break
                time.sleep(0.1)
            # sabotage the deployment: wrong replica count
            dep = fake.get("apps/v1", "Deployment", "default",
                           "ollama-model-drift")
            dep["spec"]["replicas"] = 7
            fake.update(dep)  # owned-workload watch maps back to the Model
            deadline = time.time() + 30
            while time.time() < deadline:
                dep = fake.get("apps/v1", "Deployment", "default",
                               "ollama-model-drift")
                if dep["spec"]["replicas"] == 1:
                    break
                time.sleep(0.1)
            assert dep["spec"]["replicas"] == 1
        finally:
            stop.set()
            mgr.stop()


class TestWorkQueueProcessing:
    def test_no_concurrent_processing_of_same_key(self):
        q = WorkQueue()
        q.add(("ns", "a"))
        key = q.get(timeout=1)
        assert key == ("ns", "a")
        # event arrives while a worker holds the key: must NOT hand it to
        # a second worker — marked dirty instead
        q.add(("ns", "a"))
        assert q.get(timeout=0.1) is None
        q.done(key)  # dirty → immediate requeue
        assert q.get(timeout=1) == ("ns", "a")
        q.done(("ns", "a"))
        assert q.get(timeout=0.1) is None

    def test_done_with_requeue_after(self):
        q = WorkQueue()
        q.add(("ns", "a"))
        key = q.get(timeout=1)
        q.done(key, requeue_after=0.05)
        assert q.get(timeout=1) == ("ns", "a")


class TestServerImageOverride:
    def test_spec_server_image_wins(self, fake):
        from ollama_operator_tpu.operator.reconciler import ModelReconciler
        from ollama_operator_tpu.operator.recorder import NullRecorder
        rec = ModelReconciler(fake, NullRecorder(),
                              server_image="operator-default:1")
        obj = model_obj("pinned")
        obj["spec"]["serverImage"] = "user/runtime:pin"
        fake.create(obj)
        for _ in range(12):
            rec.reconcile("default", "pinned")
            for sts in fake.list("apps/v1", "StatefulSet", "default"):
                fake.set_status("apps/v1", "StatefulSet", "default",
                                sts["metadata"]["name"],
                                {"readyReplicas":
                                 sts["spec"].get("replicas", 1)})
            for svc in fake.list("v1", "Service", "default"):
                if not svc["spec"].get("clusterIP"):
                    svc["spec"]["clusterIP"] = "10.1.1.1"
                    fake.update(svc)
            dep = fake.get("apps/v1", "Deployment", "default",
                           "ollama-model-pinned")
            if dep:
                break
        tpl = dep["spec"]["template"]["spec"]
        assert tpl["containers"][0]["image"] == "user/runtime:pin"
        assert tpl["initContainers"][0]["image"] == "user/runtime:pin"
        # the shared store keeps the operator image (it serves all models)
        sts = fake.get("apps/v1", "StatefulSet", "default",
                       "ollama-models-store")
        assert sts["spec"]["template"]["spec"]["containers"][0][
            "image"] == "operator-default:1"


class TestMetricsAuth:
    """Bearer-token gate on /metrics (parity with the reference's
    kube-rbac-proxy guard, config/default/manager_auth_proxy_patch.yaml;
    here config/default/manager_metrics_auth_patch.yaml wires a Secret
    into METRICS_TOKEN_FILE and the manager enforces it natively)."""

    def _serve(self, fake, monkeypatch, **env):
        import urllib.request
        for k in ("METRICS_TOKEN_FILE", "METRICS_TOKEN"):
            monkeypatch.delenv(k, raising=False)
        for k, v in env.items():
            monkeypatch.setenv(k, v)
        mgr = Manager(fake, namespace="default", server_image="img:t",
                      health_addr=("127.0.0.1", 0))
        httpd = mgr._health_server()
        port = httpd.server_address[1]

        def get(path, token=None):
            req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
            if token is not None:
                req.add_header("Authorization", f"Bearer {token}")
            try:
                return urllib.request.urlopen(req, timeout=10).status
            except urllib.error.HTTPError as e:
                return e.code

        return httpd, get

    def test_open_without_config(self, fake, monkeypatch):
        httpd, get = self._serve(fake, monkeypatch)
        try:
            assert get("/metrics") == 200
        finally:
            httpd.shutdown()

    def test_token_required_and_checked(self, fake, monkeypatch, tmp_path):
        tok = tmp_path / "token"
        tok.write_text("s3cret\n")
        httpd, get = self._serve(fake, monkeypatch,
                                 METRICS_TOKEN_FILE=str(tok))
        try:
            assert get("/metrics") == 401
            assert get("/metrics", token="wrong") == 401
            assert get("/metrics", token="s3cret") == 200
            assert get("/healthz") == 200          # probes stay open
        finally:
            httpd.shutdown()

    def test_missing_token_file_fails_closed(self, fake, monkeypatch,
                                             tmp_path):
        httpd, get = self._serve(
            fake, monkeypatch,
            METRICS_TOKEN_FILE=str(tmp_path / "absent"))
        try:
            assert get("/metrics") == 401
            assert get("/metrics", token="") == 401
            assert get("/healthz") == 200
        finally:
            httpd.shutdown()


class TestPollBackoff:
    def test_poll_requeues_back_off_per_model(self, fake):
        """A Model stuck at steady-state POLL backs off 5 → 7.5 → …
        capped at 60s; any shorter (progress) requeue resets its streak;
        other models are unaffected."""
        from ollama_operator_tpu.operator.reconciler import Result
        mgr = Manager(fake, namespace="default", server_image="img:t")
        seen = {}
        done_evt = threading.Event()
        real_done = mgr.queue.done

        def spy_done(key, requeue_after=-1.0):
            seen.setdefault(key, []).append(requeue_after)
            real_done(key)           # finish WITHOUT the real delay
            done_evt.set()

        mgr.queue.done = spy_done
        scripts = {"stuck": iter([5.0] * 9),
                   "moving": iter([5.0, 5.0, 0.5, 5.0])}

        class StubRec:
            def reconcile(self, ns, name):
                return Result(requeue_after=next(scripts[name]))

        mgr.reconciler = StubRec()
        t = threading.Thread(target=mgr._worker, daemon=True)
        t.start()
        try:
            for name, n in (("stuck", 9), ("moving", 4)):
                for _ in range(n):
                    done_evt.clear()
                    mgr.queue.add(("default", name))
                    assert done_evt.wait(5)
        finally:
            mgr._stop.set()
            mgr.queue.shutdown()
            t.join(timeout=5)
        stuck = seen[("default", "stuck")]
        assert stuck[:4] == [5.0, 7.5, 11.25, 16.875]
        assert stuck[-2:] == [60.0, 60.0]          # capped, stays capped
        # progress (requeue < floor) resets the streak; next POLL starts over
        assert seen[("default", "moving")] == [5.0, 7.5, 0.5, 5.0]
