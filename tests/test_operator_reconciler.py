"""Reconciler ladder tests against the fake apiserver (envtest tier).

Goes well past the reference's scaffold-level controller test
(model_controller_test.go: one Reconcile, assert no error — SURVEY.md §4
calls the coverage thin): drives the full ladder to Available by playing
kubelet (flipping workload status), and exercises the behavior fixes —
additive conditions, ReplicaFailure production, image-change reconcile,
availability revocation.
"""

import pytest

from ollama_operator_tpu.operator import workload
from ollama_operator_tpu.operator.reconciler import (DONE, KICKOFF, POLL,
                                                     ModelReconciler,
                                                     get_condition,
                                                     is_condition_true)
from ollama_operator_tpu.operator.recorder import Recorder
from ollama_operator_tpu.operator.types import API_VERSION, KIND

from fake_kube import FakeKube


class RecordingRecorder(Recorder):
    def __init__(self):
        self.events = []

    def event(self, obj, type_, reason, message):
        self.events.append((type_, reason))


@pytest.fixture()
def kube():
    return FakeKube()


@pytest.fixture()
def rec():
    return RecordingRecorder()


@pytest.fixture()
def reconciler(kube, rec):
    return ModelReconciler(kube, rec, server_image="runtime:test")


def make_model(kube, name="phi", namespace="default", **spec):
    spec.setdefault("image", "phi")
    spec.setdefault("runtime", "cpu")
    return kube.create({
        "apiVersion": API_VERSION, "kind": KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": spec,
    })


def drive(reconciler, kube, name="phi", namespace="default", max_steps=30):
    """Step the ladder, playing kubelet whenever objects appear."""
    app = workload.model_app_name(name)
    for _ in range(max_steps):
        res = reconciler.reconcile(namespace, name)
        if res == DONE:
            return res
        if kube.get("apps/v1", "StatefulSet", namespace,
                    workload.IMAGE_STORE_NAME):
            kube.set_status("apps/v1", "StatefulSet", namespace,
                            workload.IMAGE_STORE_NAME, {"readyReplicas": 1})
        svc = kube.get("v1", "Service", namespace,
                       workload.IMAGE_STORE_SERVICE)
        if svc is not None and not svc["spec"].get("clusterIP"):
            svc["spec"]["clusterIP"] = "10.0.0.1"
            kube.update(svc)
        dep = kube.get("apps/v1", "Deployment", namespace, app)
        if dep is not None:
            n = dep["spec"].get("replicas", 1)
            kube.set_status("apps/v1", "Deployment", namespace, app,
                            {"replicas": n, "readyReplicas": n,
                             "availableReplicas": n})
        sts = kube.get("apps/v1", "StatefulSet", namespace, app)
        if sts is not None:
            n = sts["spec"].get("replicas", 1)
            kube.set_status("apps/v1", "StatefulSet", namespace, app,
                            {"replicas": n, "readyReplicas": n,
                             "availableReplicas": n})
        msvc = kube.get("v1", "Service", namespace, app)
        if msvc is not None and not msvc["spec"].get("clusterIP"):
            msvc["spec"]["clusterIP"] = "10.0.0.2"
            kube.update(msvc)
    raise AssertionError("ladder did not converge")


class TestLadder:
    def test_first_reconcile_sets_progressing(self, reconciler, kube, rec):
        make_model(kube)
        res = reconciler.reconcile("default", "phi")
        assert res == KICKOFF
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "Progressing")
        assert m["status"]["conditions"][0]["type"] == "Progressing"
        assert ("Normal", "ModelCreating") in rec.events

    def test_full_ladder_to_available(self, reconciler, kube, rec):
        make_model(kube, replicas=2)
        res = drive(reconciler, kube)
        assert res == DONE
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "Available")
        assert not is_condition_true(m, "Progressing")
        # printcolumn compat: live condition first
        assert m["status"]["conditions"][0]["type"] == "Available"
        assert m["status"]["readyReplicas"] == 2
        assert ("Normal", "ModelAvailable") in rec.events
        # creation order: store trio before model workload (§3.2 ladder)
        kinds = [k for k, _ in kube.create_log]
        assert kinds.index("PersistentVolumeClaim") < \
            kinds.index("Deployment")
        # image store is namespace-singleton shared infra
        assert kube.get("apps/v1", "StatefulSet", "default",
                        "ollama-models-store") is not None

    def test_second_model_reuses_store(self, reconciler, kube):
        make_model(kube, name="a", image="phi")
        drive(reconciler, kube, name="a")
        make_model(kube, name="b", image="mistral")
        drive(reconciler, kube, name="b")
        pvcs = kube.list("v1", "PersistentVolumeClaim", "default")
        assert len(pvcs) == 1

    def test_deleted_model_is_done(self, reconciler):
        assert reconciler.reconcile("default", "ghost") == DONE

    def test_empty_image_invalid(self, reconciler, kube):
        make_model(kube, image="")
        assert reconciler.reconcile("default", "phi") == DONE
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert get_condition(m, "Progressing")["reason"] == "InvalidSpec"


class TestDriftAndFailure:
    def test_replica_scale_is_synced(self, reconciler, kube):
        make_model(kube, replicas=1)
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        m["spec"]["replicas"] = 4
        kube.update(m)
        drive(reconciler, kube)
        dep = kube.get("apps/v1", "Deployment", "default", "ollama-model-phi")
        assert dep["spec"]["replicas"] == 4

    def test_image_change_is_reconciled(self, reconciler, kube):
        """The reference ignores spec.image changes (model.go:149-186,
        SURVEY.md §2.1) — we sync the puller arg + preload env."""
        make_model(kube)
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        m["spec"]["image"] = "phi:v2"
        kube.update(m)
        drive(reconciler, kube)
        dep = kube.get("apps/v1", "Deployment", "default", "ollama-model-phi")
        tpl = dep["spec"]["template"]["spec"]
        assert tpl["initContainers"][0]["args"] == ["pull", "phi:v2"]
        env = {e["name"]: e["value"] for e in tpl["containers"][0]["env"]}
        assert env["TPU_PRELOAD_MODEL"] == "phi:v2"

    def test_replica_failure_surfaced_and_cleared(self, reconciler, kube,
                                                  rec):
        make_model(kube)
        drive(reconciler, kube)
        kube.set_status(
            "apps/v1", "Deployment", "default", "ollama-model-phi",
            {"conditions": [{"type": "ReplicaFailure", "status": "True",
                             "message": "pods \"x\" exceeded quota"}]})
        res = reconciler.reconcile("default", "phi")
        assert res == POLL
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "ReplicaFailure")
        assert not is_condition_true(m, "Available")
        assert ("Warning", "ReplicaFailure") in rec.events
        # failure resolves → Available returns, ReplicaFailure clears
        kube.set_status("apps/v1", "Deployment", "default",
                        "ollama-model-phi", {"conditions": []})
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "Available")
        assert not is_condition_true(m, "ReplicaFailure")

    def test_conditions_are_additive(self, reconciler, kube):
        make_model(kube)
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        types = {c["type"] for c in m["status"]["conditions"]}
        # reference keeps exactly one condition (§2.1 gap); we keep history
        assert {"Available", "Progressing"} <= types


class TestMultiHostLadder:
    def test_v5e16_creates_statefulset_world(self, reconciler, kube):
        make_model(kube, name="llama70b", image="llama2:70b", runtime="tpu",
                   tpu={"topology": "v5e-16"})
        drive(reconciler, kube, name="llama70b")
        sts = kube.get("apps/v1", "StatefulSet", "default",
                       "ollama-model-llama70b")
        assert sts is not None and sts["spec"]["replicas"] == 4
        heads = kube.get("v1", "Service", "default",
                         "ollama-model-llama70b-hosts")
        assert heads["spec"]["clusterIP"] == "None"
        svc = kube.get("v1", "Service", "default", "ollama-model-llama70b")
        assert svc["spec"]["selector"]["apps.kubernetes.io/pod-index"] == "0"
        m = kube.get(API_VERSION, KIND, "default", "llama70b")
        assert is_condition_true(m, "Available")
        assert m["status"]["readyReplicas"] == 4
