"""Reconciler ladder tests against the fake apiserver (envtest tier).

Goes well past the reference's scaffold-level controller test
(model_controller_test.go: one Reconcile, assert no error — SURVEY.md §4
calls the coverage thin): drives the full ladder to Available by playing
kubelet (flipping workload status), and exercises the behavior fixes —
additive conditions, ReplicaFailure production, image-change reconcile,
availability revocation.
"""

import pytest

from ollama_operator_tpu.operator import workload
from ollama_operator_tpu.operator.reconciler import (DONE, KICKOFF, POLL,
                                                     ModelReconciler,
                                                     get_condition,
                                                     is_condition_true)
from ollama_operator_tpu.operator.recorder import Recorder
from ollama_operator_tpu.operator.types import API_VERSION, KIND

from fake_kube import FakeKube


class RecordingRecorder(Recorder):
    def __init__(self):
        self.events = []

    def event(self, obj, type_, reason, message):
        self.events.append((type_, reason))


@pytest.fixture()
def kube():
    return FakeKube()


@pytest.fixture()
def rec():
    return RecordingRecorder()


@pytest.fixture()
def reconciler(kube, rec):
    return ModelReconciler(kube, rec, server_image="runtime:test")


def make_model(kube, name="phi", namespace="default", **spec):
    spec.setdefault("image", "phi")
    spec.setdefault("runtime", "cpu")
    return kube.create({
        "apiVersion": API_VERSION, "kind": KIND,
        "metadata": {"name": name, "namespace": namespace},
        "spec": spec,
    })


def drive(reconciler, kube, name="phi", namespace="default", max_steps=30):
    """Step the ladder, playing kubelet whenever objects appear."""
    app = workload.model_app_name(name)
    for _ in range(max_steps):
        res = reconciler.reconcile(namespace, name)
        if res == DONE:
            return res
        if kube.get("apps/v1", "StatefulSet", namespace,
                    workload.IMAGE_STORE_NAME):
            kube.set_status("apps/v1", "StatefulSet", namespace,
                            workload.IMAGE_STORE_NAME, {"readyReplicas": 1})
        svc = kube.get("v1", "Service", namespace,
                       workload.IMAGE_STORE_SERVICE)
        if svc is not None and not svc["spec"].get("clusterIP"):
            svc["spec"]["clusterIP"] = "10.0.0.1"
            kube.update(svc)
        dep = kube.get("apps/v1", "Deployment", namespace, app)
        if dep is not None:
            n = dep["spec"].get("replicas", 1)
            kube.set_status("apps/v1", "Deployment", namespace, app,
                            {"replicas": n, "readyReplicas": n,
                             "availableReplicas": n})
        sts = kube.get("apps/v1", "StatefulSet", namespace, app)
        if sts is not None:
            n = sts["spec"].get("replicas", 1)
            kube.set_status("apps/v1", "StatefulSet", namespace, app,
                            {"replicas": n, "readyReplicas": n,
                             "availableReplicas": n})
        msvc = kube.get("v1", "Service", namespace, app)
        if msvc is not None and not msvc["spec"].get("clusterIP"):
            msvc["spec"]["clusterIP"] = "10.0.0.2"
            kube.update(msvc)
    raise AssertionError("ladder did not converge")


class TestLadder:
    def test_first_reconcile_sets_progressing(self, reconciler, kube, rec):
        make_model(kube)
        res = reconciler.reconcile("default", "phi")
        assert res == KICKOFF
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "Progressing")
        assert m["status"]["conditions"][0]["type"] == "Progressing"
        assert ("Normal", "ModelCreating") in rec.events

    def test_full_ladder_to_available(self, reconciler, kube, rec):
        make_model(kube, replicas=2)
        res = drive(reconciler, kube)
        assert res == DONE
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "Available")
        assert not is_condition_true(m, "Progressing")
        # printcolumn compat: live condition first
        assert m["status"]["conditions"][0]["type"] == "Available"
        assert m["status"]["readyReplicas"] == 2
        assert ("Normal", "ModelAvailable") in rec.events
        # creation order: store trio before model workload (§3.2 ladder)
        kinds = [k for k, _ in kube.create_log]
        assert kinds.index("PersistentVolumeClaim") < \
            kinds.index("Deployment")
        # image store is namespace-singleton shared infra
        assert kube.get("apps/v1", "StatefulSet", "default",
                        "ollama-models-store") is not None

    def test_second_model_reuses_store(self, reconciler, kube):
        make_model(kube, name="a", image="phi")
        drive(reconciler, kube, name="a")
        make_model(kube, name="b", image="mistral")
        drive(reconciler, kube, name="b")
        pvcs = kube.list("v1", "PersistentVolumeClaim", "default")
        assert len(pvcs) == 1

    def test_deleted_model_is_done(self, reconciler):
        assert reconciler.reconcile("default", "ghost") == DONE

    def test_empty_image_invalid(self, reconciler, kube):
        make_model(kube, image="")
        assert reconciler.reconcile("default", "phi") == DONE
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert get_condition(m, "Progressing")["reason"] == "InvalidSpec"


class TestDriftAndFailure:
    def test_replica_scale_is_synced(self, reconciler, kube):
        make_model(kube, replicas=1)
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        m["spec"]["replicas"] = 4
        kube.update(m)
        drive(reconciler, kube)
        dep = kube.get("apps/v1", "Deployment", "default", "ollama-model-phi")
        assert dep["spec"]["replicas"] == 4

    def test_image_change_is_reconciled(self, reconciler, kube):
        """The reference ignores spec.image changes (model.go:149-186,
        SURVEY.md §2.1) — we sync the puller arg + preload env."""
        make_model(kube)
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        m["spec"]["image"] = "phi:v2"
        kube.update(m)
        drive(reconciler, kube)
        dep = kube.get("apps/v1", "Deployment", "default", "ollama-model-phi")
        tpl = dep["spec"]["template"]["spec"]
        assert tpl["initContainers"][0]["args"] == ["pull", "phi:v2"]
        env = {e["name"]: e["value"] for e in tpl["containers"][0]["env"]}
        assert env["TPU_PRELOAD_MODEL"] == "phi:v2"

    def test_replica_failure_surfaced_and_cleared(self, reconciler, kube,
                                                  rec):
        make_model(kube)
        drive(reconciler, kube)
        kube.set_status(
            "apps/v1", "Deployment", "default", "ollama-model-phi",
            {"conditions": [{"type": "ReplicaFailure", "status": "True",
                             "message": "pods \"x\" exceeded quota"}]})
        res = reconciler.reconcile("default", "phi")
        assert res == POLL
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "ReplicaFailure")
        assert not is_condition_true(m, "Available")
        assert ("Warning", "ReplicaFailure") in rec.events
        # failure resolves → Available returns, ReplicaFailure clears
        kube.set_status("apps/v1", "Deployment", "default",
                        "ollama-model-phi", {"conditions": []})
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "Available")
        assert not is_condition_true(m, "ReplicaFailure")

    def test_conditions_are_additive(self, reconciler, kube):
        make_model(kube)
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        types = {c["type"] for c in m["status"]["conditions"]}
        # reference keeps exactly one condition (§2.1 gap); we keep history
        assert {"Available", "Progressing"} <= types


class TestReplicaUtilizationMirror:
    """PR 10 e2e: the converged pass scrapes every workload pod's
    /api/ps (via the injectable ps_fetch) and mirrors a compact
    utilization summary into the Model CR status."""

    def _pod(self, kube, app, name, ip=None, namespace="default"):
        pod = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": name, "namespace": namespace,
                            "labels": {"app": app}},
               "status": {"phase": "Running"}}
        if ip:
            pod["status"]["podIP"] = ip
        return kube.create(pod)

    def test_status_carries_replica_utilization(self, kube, rec):
        make_model(kube)
        app = workload.model_app_name("phi")
        self._pod(kube, app, f"{app}-a", "10.1.0.5")
        calls = []

        def fake_ps(url):
            calls.append(url)
            return {"models": [{
                "name": "phi:latest",
                "lifecycle": {"state": "serving"},
                "utilization": {
                    "enabled": True, "mfu": 0.41, "goodput_tok_s": 1234.5,
                    "occupancy": 0.9, "waste_pct": 10.0,
                    "recompiles": {"decode": 1, "admit": 0}},
            }]}

        recon = ModelReconciler(kube, rec, server_image="runtime:test",
                                ps_fetch=fake_ps)
        assert drive(recon, kube) == DONE
        assert calls and calls[0] == "http://10.1.0.5:11434/api/ps"
        m = kube.get(API_VERSION, KIND, "default", "phi")
        rs = m["status"]["replicaStats"]
        assert rs["scrapedAt"]
        (entry,) = rs["replicas"]
        assert entry["pod"] == f"{app}-a" and entry["ip"] == "10.1.0.5"
        assert entry["state"] == "serving"
        assert entry["model"] == "phi:latest"
        assert entry["mfu"] == 0.41
        assert entry["goodputTokS"] == 1234.5
        assert entry["occupancy"] == 0.9
        assert entry["wastePct"] == 10.0
        assert entry["recompiles"] == 1
        # the CR stays Available — the mirror must not demote it
        assert is_condition_true(m, "Available")

    def test_unreachable_and_empty_pods_are_marked(self, kube, rec):
        make_model(kube)
        app = workload.model_app_name("phi")
        self._pod(kube, app, f"{app}-a", "10.1.0.5")   # unreachable
        self._pod(kube, app, f"{app}-b", "10.1.0.6")   # no model loaded
        self._pod(kube, app, f"{app}-c")               # no IP yet: skipped

        def fake_ps(url):
            if "10.1.0.5" in url:
                return None
            return {"models": []}

        recon = ModelReconciler(kube, rec, server_image="runtime:test",
                                ps_fetch=fake_ps)
        drive(recon, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        states = {e["pod"]: e["state"]
                  for e in m["status"]["replicaStats"]["replicas"]}
        assert states == {f"{app}-a": "unreachable",
                          f"{app}-b": "no_model"}

    def test_unchanged_stats_do_not_rewrite_status(self, kube, rec):
        make_model(kube)
        app = workload.model_app_name("phi")
        self._pod(kube, app, f"{app}-a", "10.1.0.5")
        recon = ModelReconciler(
            kube, rec, server_image="runtime:test",
            ps_fetch=lambda url: {"models": [{
                "name": "phi", "lifecycle": {"state": "serving"},
                "utilization": {"mfu": 0.1, "goodput_tok_s": 1.0,
                                "occupancy": 1.0, "waste_pct": 0.0,
                                "recompiles": {}}}]})
        drive(recon, kube)
        m1 = kube.get(API_VERSION, KIND, "default", "phi")
        assert recon.reconcile("default", "phi") == DONE
        m2 = kube.get(API_VERSION, KIND, "default", "phi")
        # identical scrape → no status write, scrapedAt untouched
        assert m2["status"]["replicaStats"] == m1["status"]["replicaStats"]

    def test_no_pods_skips_mirror(self, reconciler, kube):
        make_model(kube)
        drive(reconciler, kube)
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert "replicaStats" not in m["status"]
        assert is_condition_true(m, "Available")


class TestMultiHostLadder:
    def test_v5e16_creates_statefulset_world(self, reconciler, kube):
        make_model(kube, name="llama70b", image="llama2:70b", runtime="tpu",
                   tpu={"topology": "v5e-16"})
        drive(reconciler, kube, name="llama70b")
        sts = kube.get("apps/v1", "StatefulSet", "default",
                       "ollama-model-llama70b")
        assert sts is not None and sts["spec"]["replicas"] == 4
        heads = kube.get("v1", "Service", "default",
                         "ollama-model-llama70b-hosts")
        assert heads["spec"]["clusterIP"] == "None"
        svc = kube.get("v1", "Service", "default", "ollama-model-llama70b")
        assert svc["spec"]["selector"]["apps.kubernetes.io/pod-index"] == "0"
        m = kube.get(API_VERSION, KIND, "default", "llama70b")
        assert is_condition_true(m, "Available")
        assert m["status"]["readyReplicas"] == 4
