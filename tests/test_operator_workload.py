"""Workload-builder unit tests (pkg/model's pure functions, SURVEY.md §4a).

Covers the reference's object shapes — store trio names/sizes/mounts
(image_store.go), per-model deployment with puller init container and RO
mount (model.go, pod.go) — plus the TPU additions (resources, selectors,
multi-host env) and the deliberately fixed reference gaps
(imagePullPolicy/Secrets honored).
"""

import pytest

from ollama_operator_tpu.operator import pod as podf
from ollama_operator_tpu.operator import workload
from ollama_operator_tpu.operator.types import ModelSpecView


def model_obj(name="phi", namespace="default", **spec):
    spec.setdefault("image", "phi")
    return {
        "apiVersion": "ollama.ayaka.io/v1",
        "kind": "Model",
        "metadata": {"name": name, "namespace": namespace, "uid": "u1"},
        "spec": spec,
    }


class TestImageStore:
    def test_pvc_defaults(self):
        pvc = workload.build_store_pvc("ns1", ModelSpecView(model_obj()))
        assert pvc["metadata"]["name"] == "ollama-models-store-pvc"
        assert pvc["spec"]["accessModes"] == ["ReadWriteMany"]
        assert pvc["spec"]["resources"]["requests"]["storage"] == "100Gi"
        assert "storageClassName" not in pvc["spec"]

    def test_pvc_spec_overrides(self):
        m = model_obj(storageClassName="fast",
                      persistentVolume={"accessMode": "ReadWriteOnce"})
        pvc = workload.build_store_pvc("ns1", ModelSpecView(m))
        assert pvc["spec"]["storageClassName"] == "fast"
        assert pvc["spec"]["accessModes"] == ["ReadWriteOnce"]

    def test_store_statefulset_mounts_rw(self):
        sts = workload.build_store_statefulset(
            "ns1", ModelSpecView(model_obj()), "img:1")
        tpl = sts["spec"]["template"]["spec"]
        c = tpl["containers"][0]
        assert sts["spec"]["serviceName"] == "ollama-models-store"
        assert c["volumeMounts"][0]["readOnly"] is False
        assert {"name": "TPU_STORE_ONLY", "value": "1"} in c["env"]
        assert tpl["volumes"][0]["persistentVolumeClaim"]["claimName"] == \
            "ollama-models-store-pvc"

    def test_store_service(self):
        svc = workload.build_store_service("ns1")
        assert svc["spec"]["selector"] == {"app": "ollama-models-store"}
        assert svc["spec"]["ports"][0]["port"] == 11434


class TestModelDeployment:
    def test_basic_shape(self):
        dep = workload.build_model_deployment(model_obj(runtime="cpu"))
        assert dep["metadata"]["name"] == "ollama-model-phi"
        assert dep["spec"]["replicas"] == 1
        assert dep["spec"]["selector"]["matchLabels"] == \
            {"app": "ollama-model-phi"}
        owner = dep["metadata"]["ownerReferences"][0]
        assert owner["kind"] == "Model" and owner["uid"] == "u1"
        tpl = dep["spec"]["template"]["spec"]
        assert "nodeSelector" not in tpl  # cpu runtime: no TPU selectors
        init = tpl["initContainers"][0]
        assert init["args"] == ["pull", "phi"]
        assert init["env"][0]["value"] == "ollama-models-store.default"
        server = tpl["containers"][0]
        # blob mount RO + RW cache subPath mount layered on top
        assert server["volumeMounts"][0]["readOnly"] is True
        assert server["volumeMounts"][1]["subPath"] == "tpu-cache"
        assert server["volumeMounts"][1]["readOnly"] is False
        assert server["readinessProbe"]["httpGet"]["path"] == "/api/tags"
        assert server["readinessProbe"]["failureThreshold"] == 2500

    def test_replicas_and_pull_options_honored(self):
        m = model_obj(replicas=3, imagePullPolicy="Never",
                      imagePullSecrets=[{"name": "reg-cred"}], runtime="cpu")
        dep = workload.build_model_deployment(m)
        assert dep["spec"]["replicas"] == 3
        tpl = dep["spec"]["template"]["spec"]
        assert tpl["imagePullSecrets"] == [{"name": "reg-cred"}]
        assert tpl["containers"][0]["imagePullPolicy"] == "Never"
        assert tpl["initContainers"][0]["imagePullPolicy"] == "Never"

    def test_tpu_single_host(self):
        m = model_obj(tpu={"topology": "v5e-4"}, contextLength=8192,
                      quantization="int8", sharding={"tp": 4})
        dep = workload.build_model_deployment(m)
        tpl = dep["spec"]["template"]["spec"]
        assert tpl["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == \
            "2x2"
        assert tpl["tolerations"][0]["key"] == "google.com/tpu"
        server = tpl["containers"][0]
        assert server["resources"]["limits"]["google.com/tpu"] == "4"
        env = {e["name"]: e.get("value") for e in server["env"]}
        assert env["TPU_MAX_SEQ_LEN"] == "8192"
        assert env["TPU_ENGINE_DTYPE"] == "int8"
        assert env["TPU_KV_DTYPE"] == "int8"
        assert env["TPU_EXPECT_PLATFORM"] == "tpu"
        assert env["TPU_TENSOR_PARALLEL"] == "4"
        assert env["TPU_PRELOAD_MODEL"] == "phi"

    def test_external_pvc_used_without_creating(self):
        m = model_obj(runtime="cpu",
                      persistentVolumeClaim={"claimName": "my-claim"})
        dep = workload.build_model_deployment(m)
        vol = dep["spec"]["template"]["spec"]["volumes"][0]
        assert vol["persistentVolumeClaim"]["claimName"] == "my-claim"


class TestMultiHost:
    def test_statefulset_shape(self):
        m = model_obj(name="llama70b", image="llama2:70b",
                      tpu={"topology": "v5e-16"})
        sts = workload.build_model_statefulset(m)
        assert sts["spec"]["replicas"] == 4  # 4 hosts × 4 chips
        assert sts["spec"]["podManagementPolicy"] == "Parallel"
        assert sts["spec"]["serviceName"] == "ollama-model-llama70b-hosts"
        tpl = sts["spec"]["template"]["spec"]
        env = {e["name"]: e.get("value")
               for e in tpl["containers"][0]["env"] if "value" in e}
        assert env["TPU_DIST_HOSTS"] == "4"
        assert env["TPU_DIST_CHIPS_PER_HOST"] == "4"
        assert "ollama-model-llama70b-hosts.default.svc:8476" in \
            env["TPU_DIST_COORDINATOR"]
        assert tpl["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == \
            "4x4"

    def test_headless_service(self):
        m = model_obj(name="llama70b", tpu={"topology": "v5e-16"})
        svc = workload.build_headless_service(m)
        assert svc["spec"]["clusterIP"] == "None"
        assert svc["spec"]["publishNotReadyAddresses"] is True

    def test_serving_service_targets_host0(self):
        m = model_obj(name="llama70b", tpu={"topology": "v5e-16"})
        svc = workload.build_model_service(m)
        assert svc["spec"]["selector"][
            "apps.kubernetes.io/pod-index"] == "0"

    def test_single_host_service_has_no_index_selector(self):
        svc = workload.build_model_service(model_obj(runtime="cpu"))
        assert "apps.kubernetes.io/pod-index" not in svc["spec"]["selector"]


class TestSpecView:
    def test_defaults(self):
        v = ModelSpecView(model_obj())
        assert v.replicas == 1 and v.runtime == "tpu"
        assert v.tpu_placement().topology == "v5e-1"

    def test_unknown_topology_rejected(self):
        v = ModelSpecView(model_obj(tpu={"topology": "v9-999"}))
        with pytest.raises(ValueError, match="unknown tpu.topology"):
            v.tpu_placement()

    def test_cpu_runtime_no_placement(self):
        assert ModelSpecView(model_obj(runtime="cpu")).tpu_placement() is None


class TestDriftDetection:
    """update_model_workload must not see apiserver defaulting as drift
    (a real apiserver enriches live pod templates with defaulted fields),
    but must catch real template changes via the spec-hash annotation."""

    def _mk(self):
        from ollama_operator_tpu.operator.recorder import NullRecorder
        from fake_kube import FakeKube
        kube = FakeKube()
        m = model_obj(runtime="cpu")
        want = workload.build_model_deployment(m, "img:1")
        workload.stamp_spec_hash(want)
        kube.create(want)
        return kube, m, want

    def test_apiserver_defaulting_is_not_drift(self):
        from ollama_operator_tpu.operator.recorder import NullRecorder
        kube, m, want = self._mk()
        cur = kube.get("apps/v1", "Deployment", "default", "ollama-model-phi")
        # simulate apiserver defaulting on the live object
        tpl = cur["spec"]["template"]["spec"]
        tpl["dnsPolicy"] = "ClusterFirst"
        for c in tpl["containers"]:
            c["terminationMessagePath"] = "/dev/termination-log"
            c.setdefault("resources", {})
        kube.update(cur)
        cur = kube.get("apps/v1", "Deployment", "default", "ollama-model-phi")
        rec = NullRecorder()
        assert workload.update_model_workload(kube, rec, m, cur, want) is False
        assert rec._events == []

    def test_real_template_change_is_drift(self):
        from ollama_operator_tpu.operator.recorder import NullRecorder
        kube, m, _ = self._mk()
        m2 = model_obj(runtime="cpu", image="phi:v2")
        want2 = workload.build_model_deployment(m2, "img:1")
        workload.stamp_spec_hash(want2)
        cur = kube.get("apps/v1", "Deployment", "default", "ollama-model-phi")
        assert workload.update_model_workload(
            kube, NullRecorder(), m2, cur, want2) is True
        cur = kube.get("apps/v1", "Deployment", "default", "ollama-model-phi")
        assert cur["spec"]["template"]["spec"]["initContainers"][0][
            "args"] == ["pull", "phi:v2"]
        assert cur["metadata"]["annotations"][
            workload.SPEC_HASH_ANNOTATION] == workload.spec_hash(want2)


class TestProbes:
    def test_liveness_fails_fast_startup_tolerates_load(self):
        dep = workload.build_model_deployment(model_obj(runtime="cpu"))
        server = dep["spec"]["template"]["spec"]["containers"][0]
        assert server["startupProbe"]["failureThreshold"] == 2500
        assert server["livenessProbe"]["failureThreshold"] == 3
        assert server["livenessProbe"]["httpGet"]["path"] == "/livez"
