"""Paged KV cache: engine parity vs the dense cache, page accounting,
pallas paged-kernel parity (interpret), preemption + requeue.

Round-1 VERDICT weak #3: the dense slot cache reserved max_seq_len per
slot and capped concurrency at max_slots. The paged pool decouples both —
these tests pin the invariants (SURVEY.md §7 hard-part 2).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.models.config import PRESETS
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.paged import PageTable, PagesExhausted
from ollama_operator_tpu.runtime.scheduler import Scheduler

BASE = PRESETS["tiny"]
XLA = dataclasses.replace(BASE, kernels="xla")
INTERP = dataclasses.replace(BASE, kernels="interpret")
GREEDY = SlotOptions(temperature=0.0)
DENSE = EngineConfig(max_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
                     min_prefill_bucket=16)
PAGED = dataclasses.replace(DENSE, paged=True, page_size=8)

PROMPT = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
P2 = np.array([7, 7, 7], np.int32)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(BASE, jax.random.key(0), jnp.float32)


def _greedy_run(cfg, ecfg, params):
    eng = Engine(cfg, params, ecfg=ecfg)
    seq = [eng.admit(0, PROMPT, GREEDY), eng.admit(1, P2, GREEDY)]
    for _ in range(3):
        t = eng.decode()
        seq.extend([int(t[0]), int(t[1])])
    seq.extend(int(x) for x in eng.decode_n(4)[:, :2].ravel())
    return seq


def test_page_table_accounting():
    pt = PageTable(n_slots=2, n_pages=5, page_size=8, max_blocks=8)
    assert pt.n_free == 4                      # page 0 is trash
    assert pt.grow(0, 17)                      # 3 blocks
    assert pt.owned_blocks(0) == 3 and pt.n_free == 1
    assert pt.grow(0, 20)                      # still 3 blocks — no-op
    assert not pt.grow(1, 17)                  # needs 3, only 1 free
    assert pt.owned_blocks(1) == 0             # failed grow allocs nothing
    assert pt.grow(1, 8)
    pt.release(0)
    assert pt.n_free == 3
    assert (pt.tables[0] == 0).all()


@pytest.mark.parametrize("kernels,cache_dtype", [
    ("xla", jnp.float32),
    ("interpret", jnp.float32),   # pallas paged kernel, interpreted
    ("xla", jnp.int8),
    ("interpret", jnp.int8),      # int8 pages + lane-wise scales in-kernel
])
def test_paged_engine_matches_dense(params, kernels, cache_dtype):
    cfg = dataclasses.replace(BASE, kernels=kernels)
    dense = dataclasses.replace(DENSE, cache_dtype=cache_dtype)
    paged = dataclasses.replace(PAGED, cache_dtype=cache_dtype)
    ref = _greedy_run(XLA, dense, params)
    got = _greedy_run(cfg, paged, params)
    assert got == ref, (got, ref)


def test_paged_pool_smaller_than_dense(params):
    """A pool far below max_slots*max_seq still serves (HBM decoupling)."""
    small = dataclasses.replace(PAGED, n_pages=8)   # 64 tokens total
    ref = _greedy_run(XLA, DENSE, params)
    assert _greedy_run(XLA, small, params) == ref


def test_paged_extend_matches_dense(params):
    def run(ecfg):
        eng = Engine(XLA, params, ecfg=ecfg)
        first = eng.admit(0, PROMPT, GREEDY)
        toks = [first] + [int(eng.decode()[0]) for _ in range(3)]
        eng.release(0, park=True)
        full = np.concatenate([PROMPT, np.asarray(toks[:-1], np.int32),
                               np.array([11, 12], np.int32)])
        t2 = eng.extend(0, full, start=len(PROMPT) + 3, opts=GREEDY)
        return toks, [t2] + [int(eng.decode()[0]) for _ in range(2)]

    assert run(PAGED) == run(DENSE)


def test_paged_int8_extend_works(params):
    """int8 × prefix-cache was mutually exclusive on the dense cache
    (round-1 weak #4); the paged pool closes the combination."""
    q_paged = dataclasses.replace(PAGED, cache_dtype=jnp.int8)
    eng = Engine(XLA, params, ecfg=q_paged)
    assert eng.supports_extend
    first = eng.admit(0, PROMPT, GREEDY)
    toks = [first] + [int(eng.decode()[0]) for _ in range(3)]
    eng.release(0, park=True)
    full = np.concatenate([PROMPT, np.asarray(toks[:-1], np.int32),
                           np.array([11, 12], np.int32)])
    t2 = eng.extend(0, full, start=len(PROMPT) + 3, opts=GREEDY)
    out = [t2] + [int(eng.decode()[0]) for _ in range(2)]
    assert len(out) == 3 and all(isinstance(t, int) for t in out)


def test_engine_preemption_victims_newest_first(params):
    eng = Engine(XLA, params, ecfg=dataclasses.replace(PAGED, n_pages=5))
    eng.admit(0, PROMPT, GREEDY)
    eng.admit(1, PROMPT, GREEDY)
    eng.admit(2, P2, GREEDY)
    victims = eng.prepare_decode(8)
    assert victims and victims[0] == 2        # newest admission loses
    with pytest.raises(PagesExhausted):
        eng.decode_n(8)
    for v in victims:
        eng.release(v)
    assert eng.prepare_decode(8) == []
    eng.decode_n(8)                           # survivors keep decoding


def test_paged_dp_mesh_matches_single_device(params):
    """paged×dp (round-2 VERDICT next-4): slots on BOTH dp shards decode
    the same greedy tokens as a single-device paged engine — per-shard
    sub-pools with local tables must be invisible to outputs."""
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh

    def run(mesh):
        eng = Engine(XLA, params, mesh=mesh, ecfg=PAGED)
        seq = [eng.admit(0, PROMPT, GREEDY), eng.admit(1, P2, GREEDY),
               eng.admit(2, PROMPT[:5], GREEDY)]   # slot 2 = shard 1
        for _ in range(3):
            t = eng.decode()
            seq.extend(int(t[i]) for i in range(3))
        seq.extend(int(x) for x in eng.decode_n(4)[:, :3].ravel())
        return seq

    mesh = make_mesh(MeshPlan(dp=2), jax.devices()[:2])
    assert run(mesh) == run(None)


def test_paged_dp_per_shard_pool_accounting(params):
    """Each dp shard allocates from its OWN sub-pool: filling shard 0
    must not consume shard 1's pages, and a shard-0 overflow raises while
    shard 1 still admits."""
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
    mesh = make_mesh(MeshPlan(dp=2), jax.devices()[:2])
    # 4 data pages per shard (8 total), page_size 8, 4 slots -> 2 per shard
    eng = Engine(XLA, params, mesh=mesh,
                 ecfg=dataclasses.replace(PAGED, n_pages=8))
    assert eng.free_pages == 8
    eng.admit(0, PROMPT, GREEDY)                   # shard 0: 1 page + room
    free_s1_before = eng._pt.free_for(2)
    with pytest.raises(PagesExhausted):
        # needs 4 pages (25 tokens + chunk headroom) > shard 0's 3 left
        eng.admit(1, np.arange(1, 26, dtype=np.int32), GREEDY)
    assert eng._pt.free_for(2) == free_s1_before   # shard 1 untouched
    eng.admit(2, PROMPT, GREEDY)                   # shard 1 still admits
    t = eng.decode()
    assert t.shape == (4,)


def test_extend_pages_exhausted_releases_prefix(params):
    """A failed extend must hand the parked prefix's pages back to the
    pool: the scheduler has already dropped the slot from its parked map,
    so nothing else would ever free them (ADVICE r2)."""
    eng = Engine(XLA, params, ecfg=dataclasses.replace(PAGED, n_pages=3))
    eng.admit(0, PROMPT, GREEDY)              # 1 page (+ chunk headroom)
    eng.release(0, park=True)                 # prefix keeps its page
    held = eng._pt.owned_blocks(0)
    assert held > 0
    full = np.concatenate([PROMPT, np.arange(1, 25, dtype=np.int32)])
    with pytest.raises(PagesExhausted):
        eng.extend(0, full, start=len(PROMPT), opts=GREEDY)
    assert eng._pt.owned_blocks(0) == 0
    assert eng.free_pages == 3                # whole pool free again


def test_admission_pages_exhausted(params):
    eng = Engine(XLA, params, ecfg=dataclasses.replace(PAGED, n_pages=2))
    eng.admit(0, PROMPT, GREEDY)              # 1 page
    with pytest.raises(PagesExhausted):
        eng.admit(1, np.arange(1, 12, dtype=np.int32), GREEDY)  # needs 2
    assert not eng.admissible(17)             # 3 blocks > 2 total


def test_scheduler_preempts_and_resumes(params):
    """More concurrent work than the pool can hold at once: the scheduler
    preempts the newest request, requeues it, and EVERY request still
    finishes with its full token budget on the same output stream."""
    eng = Engine(XLA, params, ecfg=dataclasses.replace(
        PAGED, max_slots=3, n_pages=6))
    sched = Scheduler(eng)
    try:
        reqs = [sched.submit(PROMPT + i, max_tokens=12,
                             opts=SlotOptions(temperature=0.0))
                for i in range(3)]
        outs = [list(r.tokens()) for r in reqs]
        for r, out in zip(reqs, outs):
            assert r.error is None
            assert len(out) == 12, (len(out), r.error)
        # 3 slots × (8 prompt + 12 gen) = 60 tokens > 48 page slots → at
        # least one preemption (or parked eviction) must have happened
        assert sched.n_preemptions >= 1
    finally:
        sched.shutdown()


def test_scheduler_paged_full_flow_no_pressure(params):
    """Ample pool: paged scheduler behaves exactly like the dense one."""
    def run(ecfg):
        eng = Engine(XLA, params, ecfg=ecfg)
        sched = Scheduler(eng)
        try:
            reqs = [sched.submit(PROMPT + i, max_tokens=6,
                                 opts=SlotOptions(temperature=0.0))
                    for i in range(4)]
            return [list(r.tokens()) for r in reqs]
        finally:
            sched.shutdown()

    assert run(PAGED) == run(DENSE)


@pytest.mark.parametrize("kernels,cache_dtype", [
    ("interpret", jnp.float32),
    ("interpret", jnp.int8),
])
def test_paged_engine_mha_matches_dense(kernels, cache_dtype):
    """MHA pools (G=1) route through the VPU paged kernel branch
    (_paged_kernel_mha — no per-head dots); greedy output must match the
    dense engine. KvH=8 keeps the sublane-alignment gate satisfied."""
    mha_cfg = dataclasses.replace(BASE, n_heads=8, n_kv_heads=8,
                                  kernels=kernels)
    mha_xla = dataclasses.replace(mha_cfg, kernels="xla")
    p = decoder.init_params(mha_cfg, jax.random.key(3), jnp.float32)
    dense = dataclasses.replace(DENSE, cache_dtype=cache_dtype)
    paged = dataclasses.replace(PAGED, cache_dtype=cache_dtype)
    ref = _greedy_run(mha_xla, dense, p)
    got = _greedy_run(mha_cfg, paged, p)
    assert got == ref, (got, ref)


# ---------------------------------------------------------------------------
# v3 live-page async-DMA kernel (VERDICT r3 next-step #1)
# ---------------------------------------------------------------------------

def _rand_pool(key, L, P, KvH, ps, hd, quant):
    k1, k2 = jax.random.split(key)
    kf = jax.random.normal(k1, (L, P, KvH, ps, hd), jnp.float32)
    vf = jax.random.normal(k2, (L, P, KvH, ps, hd), jnp.float32)
    if not quant:
        return kf, vf
    from ollama_operator_tpu.ops import quant_cache as QC

    def q(pool):
        qq, ss = QC.quantize_kv(pool)     # per-position scales [...,ps]
        return {"q": qq, "s": ss}
    return q(kf), q(vf)


@pytest.mark.parametrize("quant", [False, True])
@pytest.mark.parametrize("kvh,h", [(2, 8), (4, 4)])   # GQA and MHA
def test_paged_v3_matches_v2_direct(quant, kvh, h, monkeypatch):
    """Kernel-level parity: the dynamic live-page walk + KvH-batched dots
    must reproduce the v2 grid kernel bit-for-bit-ish on mixed lengths,
    both pool dtypes, GQA and MHA."""
    from ollama_operator_tpu.ops.pallas.paged import (
        paged_decode_attention, paged_decode_attention_v3)
    # the dispatcher routes to v3/v4 by default — the REFERENCE must be
    # the v2 grid kernel, not a self-comparison
    monkeypatch.setenv("TPU_PAGED_V3", "0")
    monkeypatch.setenv("TPU_PAGED_V4", "0")
    L, P, ps, hd, B = 2, 9, 8, 128, 4
    key = jax.random.key(0)
    kp, vp = _rand_pool(key, L, P, kvh, ps, hd, quant)
    q = jax.random.normal(jax.random.key(1), (B, 1, h, hd), jnp.float32)
    tables = jnp.asarray(
        np.random.default_rng(0).permutation(np.arange(1, 9))
        .reshape(B, 2), jnp.int32)
    lengths = jnp.asarray([0, 3, 8, 15], jnp.int32)
    layer = jnp.asarray([1], jnp.int32)
    ref = paged_decode_attention(q, kp, vp, layer, tables, lengths,
                                 scale=0.35, nblk=2, interpret=True)
    got = paged_decode_attention_v3(q, kp, vp, layer, tables, lengths,
                                    scale=0.35, nblk=2, interpret=True)
    assert ref is not None and got is not None
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_v3_sliding_window_matches_v2(monkeypatch):
    from ollama_operator_tpu.ops.pallas.paged import (
        paged_decode_attention, paged_decode_attention_v3)
    monkeypatch.setenv("TPU_PAGED_V3", "0")
    monkeypatch.setenv("TPU_PAGED_V4", "0")
    L, P, KvH, ps, hd, B, H = 1, 9, 2, 8, 128, 4, 4
    kp, vp = _rand_pool(jax.random.key(2), L, P, KvH, ps, hd, False)
    q = jax.random.normal(jax.random.key(3), (B, 1, H, hd), jnp.float32)
    tables = jnp.asarray(np.arange(1, 9).reshape(B, 2), jnp.int32)
    lengths = jnp.asarray([2, 9, 12, 15], jnp.int32)
    layer = jnp.asarray([0], jnp.int32)
    for win in (4, 11):
        ref = paged_decode_attention(q, kp, vp, layer, tables, lengths,
                                     scale=0.3, sliding_window=win,
                                     nblk=2, interpret=True)
        got = paged_decode_attention_v3(q, kp, vp, layer, tables, lengths,
                                        scale=0.3, sliding_window=win,
                                        nblk=2, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5, err_msg=f"win={win}")


@pytest.mark.parametrize("cache_dtype", [jnp.float32, jnp.int8])
def test_paged_v3_engine_matches_dense(params, cache_dtype, monkeypatch):
    """End-to-end: the engine's greedy decode through the v3 kernel equals
    the dense-cache reference (same invariant the v2 kernel pins)."""
    monkeypatch.setenv("TPU_PAGED_V3", "1")
    dense = dataclasses.replace(DENSE, cache_dtype=cache_dtype)
    paged = dataclasses.replace(PAGED, cache_dtype=cache_dtype)
    ref = _greedy_run(XLA, dense, params)
    got = _greedy_run(INTERP, paged, params)
    assert got == ref, (got, ref)


# ---------------------------------------------------------------------------
# v4 compacted flat-grid kernel (round 5: the B=32 walk-serialization floor)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("quant", [False, True])
@pytest.mark.parametrize("kvh,h", [(2, 8), (4, 4)])   # GQA and MHA
def test_paged_v4_matches_v2_direct(quant, kvh, h, monkeypatch):
    """Kernel-level parity for the flat-grid formulation: the slot-sorted
    live-page list (cumsum + searchsorted construction, dead tail frozen)
    must reproduce the v2 grid kernel on mixed lengths, both pool dtypes,
    GQA and MHA."""
    from ollama_operator_tpu.ops.pallas.paged import (
        paged_decode_attention, paged_decode_attention_v4)
    # pin the reference to the v2 grid kernel (the dispatcher would
    # otherwise hand back v3 — or v4 itself under TPU_PAGED_V4=1)
    monkeypatch.setenv("TPU_PAGED_V3", "0")
    monkeypatch.setenv("TPU_PAGED_V4", "0")
    L, P, ps, hd, B = 2, 9, 8, 128, 4
    key = jax.random.key(0)
    kp, vp = _rand_pool(key, L, P, kvh, ps, hd, quant)
    q = jax.random.normal(jax.random.key(1), (B, 1, h, hd), jnp.float32)
    tables = jnp.asarray(
        np.random.default_rng(0).permutation(np.arange(1, 9))
        .reshape(B, 2), jnp.int32)
    lengths = jnp.asarray([0, 3, 8, 15], jnp.int32)
    layer = jnp.asarray([1], jnp.int32)
    ref = paged_decode_attention(q, kp, vp, layer, tables, lengths,
                                 scale=0.35, nblk=2, interpret=True)
    got = paged_decode_attention_v4(q, kp, vp, layer, tables, lengths,
                                    scale=0.35, nblk=2, interpret=True)
    assert ref is not None and got is not None
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_v4_sliding_window_matches_v2(monkeypatch):
    from ollama_operator_tpu.ops.pallas.paged import (
        paged_decode_attention, paged_decode_attention_v4)
    monkeypatch.setenv("TPU_PAGED_V3", "0")
    monkeypatch.setenv("TPU_PAGED_V4", "0")
    L, P, KvH, ps, hd, B, H = 1, 9, 2, 8, 128, 4, 4
    kp, vp = _rand_pool(jax.random.key(2), L, P, KvH, ps, hd, False)
    q = jax.random.normal(jax.random.key(3), (B, 1, H, hd), jnp.float32)
    tables = jnp.asarray(np.arange(1, 9).reshape(B, 2), jnp.int32)
    lengths = jnp.asarray([2, 9, 12, 15], jnp.int32)
    layer = jnp.asarray([0], jnp.int32)
    for win in (4, 11):
        ref = paged_decode_attention(q, kp, vp, layer, tables, lengths,
                                     scale=0.3, sliding_window=win,
                                     nblk=2, interpret=True)
        got = paged_decode_attention_v4(q, kp, vp, layer, tables, lengths,
                                        scale=0.3, sliding_window=win,
                                        nblk=2, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5, err_msg=f"win={win}")


@pytest.mark.parametrize("cache_dtype", [jnp.float32, jnp.int8])
def test_paged_v4_engine_matches_dense(params, cache_dtype, monkeypatch):
    """End-to-end: the engine's greedy decode through the v4 kernel equals
    the dense-cache reference (same invariant v2/v3 pin)."""
    monkeypatch.setenv("TPU_PAGED_V4", "1")
    dense = dataclasses.replace(DENSE, cache_dtype=cache_dtype)
    paged = dataclasses.replace(PAGED, cache_dtype=cache_dtype)
    ref = _greedy_run(XLA, dense, params)
    got = _greedy_run(INTERP, paged, params)
    assert got == ref, (got, ref)


@pytest.mark.chaos
def test_preemption_then_engine_error_stays_consistent(params):
    """Decode failure while requests sit preempted: the supervised
    restart must not corrupt resume state. Every stream either finishes
    with its FULL token budget (resume_ids intact through the rebuild)
    or errors cleanly exactly once — and the scheduler keeps serving."""
    import queue as queue_mod
    import time

    eng = Engine(XLA, params, ecfg=dataclasses.replace(
        PAGED, max_slots=3, n_pages=6))
    sched = Scheduler(eng, restart_backoff=0.001)
    real_launch = eng.decode_n_launch
    fired = {"x": False}

    def post_preempt_boom(n=None, **kw):
        # fail exactly once, at the first decode AFTER a preemption has
        # happened — deterministically exercises restart-with-preempted.
        # Patched at the LAUNCH point so both the sync path (decode_n
        # calls through it) and paged async double-buffering hit it.
        if sched.n_preemptions >= 1 and not fired["x"]:
            fired["x"] = True
            raise RuntimeError("post-preempt boom")
        return real_launch(n, **kw)

    eng.decode_n_launch = post_preempt_boom
    try:
        reqs = [sched.submit(PROMPT + i, max_tokens=12,
                             opts=SlotOptions(temperature=0.0))
                for i in range(3)]
        outs, errs = [], []
        for r in reqs:
            try:
                outs.append(list(r.tokens()))
            except RuntimeError as e:
                assert "post-preempt boom" in str(e)
                errs.append(r)
            # exactly once: nothing queued after the terminal item
            with pytest.raises(queue_mod.Empty):
                r.out.get_nowait()
        assert fired["x"], "pressure never triggered a preemption"
        assert sched.n_preemptions >= 1
        # clean split: full budget or clean error, nothing in between
        for out in outs:
            assert len(out) == 12
        assert len(outs) + len(errs) == 3
        assert not sched.broken
        deadline = time.monotonic() + 5
        while sched.n_active and time.monotonic() < deadline:
            time.sleep(0.01)
        # page accounting survived the rebuild: pool fully free again
        assert sched.n_active == 0
        r2 = sched.submit(PROMPT, max_tokens=12,
                          opts=SlotOptions(temperature=0.0))
        assert len(list(r2.tokens())) == 12
    finally:
        sched.shutdown()
