"""Paged async dispatch: epoch-fenced page reclamation (ISSUE 5).

Coverage: PageTable quarantine lifecycle (stamp/retire/drain, sync
pass-through, check() invariants), engine-level fencing of release /
donation / radix eviction while a dispatch is in flight, the scheduler
double-buffering in paged mode with bit-identical async-vs-sync streams
(greedy AND seeded, with and without a radix hit), quarantine
convergence under pool pressure (preemption in flight), the async
fallback observability counter, and the engine.step chaos drill
(fail:after=1 in paged+async: owners errored exactly once, restart
drains the quarantine, accounting stays clean).
"""

import dataclasses
import queue as queue_mod
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.models.config import PRESETS
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.paged import PageTable
from ollama_operator_tpu.runtime.scheduler import Scheduler
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

BASE = PRESETS["tiny"]
XLA = dataclasses.replace(BASE, kernels="xla")
GREEDY = SlotOptions(temperature=0.0)
SEEDED = SlotOptions(temperature=0.9, top_k=40)
DENSE = EngineConfig(max_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
                     min_prefill_bucket=16)
PAGED = dataclasses.replace(DENSE, paged=True, page_size=8)

PREFIX = np.arange(1, 25, dtype=np.int32)          # 24 tokens = 3 pages
PROMPT = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(BASE, jax.random.key(0), jnp.float32)


def _drain(sched, deadline_s=5.0):
    t1 = time.monotonic() + deadline_s
    while ((sched.n_active or sched.engine.quarantined_pages)
           and time.monotonic() < t1):
        time.sleep(0.01)
    assert sched.n_active == 0
    assert sched.engine.quarantined_pages == 0


# ---------------------------------------------------------------------------
# quarantine lifecycle on the bare page table (no engine)
# ---------------------------------------------------------------------------

def test_sync_reclaim_is_passthrough():
    """With no dispatch in flight (epoch == retired) frees keep today's
    exact semantics: straight to the free list, quarantine untouched."""
    pt = PageTable(n_slots=2, n_pages=6, page_size=8, max_blocks=8)
    assert pt.grow(0, 16)
    pt.release(0)
    assert pt.quarantined == 0 and pt.n_free == 5
    # retiring up to date keeps the fence open
    e = pt.advance_epoch()
    pt.retire_epoch(e)
    assert pt.grow(0, 8)
    pt.release(0)
    assert pt.quarantined == 0 and pt.n_free == 5
    pt.check()


def test_quarantine_stamps_and_partial_retire():
    """Frees during epoch N are stamped N and become allocatable only
    when N retires — retiring e1 must not release e2's pages."""
    pt = PageTable(n_slots=2, n_pages=6, page_size=8, max_blocks=8)
    assert pt.grow(0, 8) and pt.grow(1, 8)
    e1 = pt.advance_epoch()
    pt.release(0)                              # stamped e1
    e2 = pt.advance_epoch()
    pt.release(1)                              # stamped e2
    assert pt.quarantined == 2 and pt.n_free == 3
    pt.check()
    pt.retire_epoch(e1)
    assert pt.quarantined == 1 and pt.n_free == 4
    pt.retire_epoch(e2)
    assert pt.quarantined == 0 and pt.n_free == 5
    # retire clamps to the launched epoch and is idempotent
    pt.retire_epoch(999)
    pt.check()


def test_drain_quarantine_reclaims_everything():
    pt = PageTable(n_slots=2, n_pages=6, page_size=8, max_blocks=8)
    assert pt.grow(0, 16)
    pt.advance_epoch()
    pt.release(0)
    assert pt.quarantined == 2
    assert pt.drain_quarantine() == 2
    assert pt.quarantined == 0 and pt.n_free == 5
    pt.check()


def test_unpin_routes_through_the_fence():
    """Radix eviction frees via unpin: with a dispatch in flight the page
    must quarantine, not return to the pool."""
    pt = PageTable(n_slots=2, n_pages=6, page_size=8, max_blocks=8)
    assert pt.grow(0, 8)
    pg = pt.slot_pages(0)[0]
    pt.pin(pg)                                 # the tree adopts it
    pt.release(0)
    assert pt.n_free == 4                      # pinned: stays resident
    pt.advance_epoch()                         # a dispatch is in flight
    pt.unpin(pg)                               # LRU eviction
    assert pt.quarantined == 1 and pt.n_free == 4
    pt.check()
    pt.drain_quarantine()
    assert pt.n_free == 5
    pt.check()


def test_check_catches_free_and_quarantined():
    pt = PageTable(n_slots=1, n_pages=4, page_size=8, max_blocks=4)
    assert pt.grow(0, 8)
    pg = pt.slot_pages(0)[0]
    pt.advance_epoch()
    pt.release(0)
    assert pt.quarantined == 1
    pt._free.append(pg)                        # corrupt: free AND fenced
    with pytest.raises(AssertionError):
        pt.check()
    pt._free.pop()                             # restore sanity
    pt.drain_quarantine()
    pt.check()


def test_check_catches_referenced_while_quarantined():
    pt = PageTable(n_slots=1, n_pages=4, page_size=8, max_blocks=4)
    assert pt.grow(0, 8)
    pg = pt.slot_pages(0)[0]
    pt.advance_epoch()
    pt.release(0)
    pt._rc[pg] = 1                             # corrupt: live ref in fence
    with pytest.raises(AssertionError):
        pt.check()
    pt._rc[pg] = 0                             # restore sanity
    pt.drain_quarantine()
    pt.check()


# ---------------------------------------------------------------------------
# engine: frees while a dispatch is genuinely in flight
# ---------------------------------------------------------------------------

def test_release_in_flight_quarantines_then_retires(params):
    """A slot released while a launched chunk is still un-materialised
    must fence its pages; the next launch's retire= ack (the epoch the
    caller already waited on) unfences them."""
    eng = Engine(XLA, params, ecfg=PAGED)
    eng.admit(0, PROMPT, GREEDY)
    eng.admit(1, PROMPT + 1, GREEDY)
    h1 = eng.decode_n_launch()
    assert h1.epoch == 1
    eng.release(1)                             # in flight: must fence
    assert eng.quarantined_pages >= 1
    eng._pt.check()
    h1.wait()
    h2 = eng.decode_n_launch(retire=h1.epoch)  # ack unfences stamp<=1
    assert eng.quarantined_pages == 0
    h2.wait()
    assert eng.fence_quiesce() == 0            # nothing left to drain
    eng.release(0)                             # sync again: direct free
    assert eng.quarantined_pages == 0
    assert eng.free_pages == eng._pt.data_pages
    eng._pt.check()


def test_donate_and_evict_in_flight_route_through_fence(params):
    """Radix donation (duplicate/tail frees) and LRU eviction (unpins)
    while a chunk is in flight must quarantine; fence_quiesce reclaims
    the whole pool once the program materialises."""
    eng = Engine(XLA, params, ecfg=PAGED)
    assert eng.radix_enabled
    donor = np.arange(1, 29, dtype=np.int32)   # 28 tokens
    first = eng.admit(0, donor, GREEDY)
    rows = eng.decode_n(4)                     # sync: epoch==retired
    gen = [first] + [int(r[0]) for r in rows]
    handle = eng.decode_n_launch()             # NOW a program is in flight
    eng.donate_prefix(0, list(donor) + gen[:-1])   # 32 tokens = 4 pages
    assert eng.radix_nodes == 4
    assert eng.quarantined_pages >= 1          # the slot's tail pages
    eng._pt.check()
    n_evicted = eng.radix_evict(10)            # unpin all 4 tree pages
    assert n_evicted == 4
    assert eng.radix_nodes == 0
    assert eng.quarantined_pages >= 5
    eng._pt.check()
    handle.wait()
    assert eng.fence_quiesce() >= 5
    assert eng.quarantined_pages == 0
    assert eng.free_pages == eng._pt.data_pages
    eng._pt.check()


# ---------------------------------------------------------------------------
# scheduler: double-buffered paged decode, stream parity
# ---------------------------------------------------------------------------

def test_paged_scheduler_double_buffers_by_default(params):
    """The `and not engine.paged` gate is gone: a paged scheduler with
    TPU_ASYNC_DISPATCH unset/on runs double-buffered."""
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng, async_dispatch=True)
    try:
        assert sched.async_dispatch
        out = list(sched.submit(PROMPT, max_tokens=6, opts=GREEDY).tokens())
        assert len(out) == 6
        _drain(sched)
    finally:
        sched.shutdown()


def _arm(params, async_on, warm):
    """One scheduler arm: optional warm donor (radix hit for the probes),
    then greedy + seeded probes sharing PREFIX. Returns all streams."""
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng, async_dispatch=async_on)
    try:
        assert sched.async_dispatch is async_on
        outs = []
        if warm:
            donor = np.concatenate([PREFIX, np.array([60, 61], np.int32)])
            outs.append(list(sched.submit(donor, max_tokens=4,
                                          opts=GREEDY).tokens()))
        probes = [
            (np.concatenate([PREFIX, np.array([70], np.int32)]), GREEDY),
            (np.concatenate([PREFIX, np.array([70], np.int32)]), SEEDED),
            (PROMPT, GREEDY),
        ]
        reqs = [sched.submit(p, max_tokens=8, opts=o) for p, o in probes]
        outs += [list(r.tokens()) for r in reqs]
        for r in reqs:
            assert r.error is None
        if warm:
            assert any(r.stats.n_reused >= 16 for r in reqs)
        _drain(sched)
        return outs
    finally:
        sched.shutdown()


@pytest.mark.parametrize("warm", [False, True],
                         ids=["cold", "radix-hit"])
def test_paged_async_streams_match_sync(params, warm):
    """The acceptance bar: paged async streams are bit-identical to the
    sync path — greedy and seeded, cold and with a radix stitch."""
    assert _arm(params, True, warm) == _arm(params, False, warm)


def test_preempt_under_pressure_in_flight_converges(params):
    """Pool pressure with async double-buffering: preemption and
    eviction route through the fence (drain-then-unfence before any
    sacrifice), every stream still gets its full budget, and the
    quarantine is empty once the dust settles."""
    eng = Engine(XLA, params, ecfg=dataclasses.replace(
        PAGED, max_slots=3, n_pages=6))
    sched = Scheduler(eng, async_dispatch=True)
    try:
        assert sched.async_dispatch
        reqs = [sched.submit(PROMPT + i, max_tokens=12, opts=GREEDY)
                for i in range(3)]
        outs = [list(r.tokens()) for r in reqs]
        for r, out in zip(reqs, outs):
            assert r.error is None
            assert len(out) == 12, (len(out), r.error)
        _drain(sched)
        assert eng.free_pages == eng._pt.data_pages - eng.radix_pages
        eng._pt.check()
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# fallback observability
# ---------------------------------------------------------------------------

def test_async_fallback_counter_preseeded():
    """Every cause label exists at 0 before any fallback fires: alert
    rules rate() over these and a series that first appears AT the first
    fallback hides it."""
    text = METRICS.render()
    for cause in ("grammar", "spec", "paged_dp"):
        assert f'tpu_model_async_fallback_total{{cause="{cause}"}}' in text


def test_paged_dp_double_buffers(params):
    """cause="paged_dp" retired: a dp-sharded paged pool keeps async
    dispatch (epochs are global, quarantines per-shard — the fence never
    crosses the shard boundary) and the counter stays at its pre-seeded
    zero. Streams match the sync arm bit-for-bit."""
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh

    def arm(async_on):
        mesh = make_mesh(MeshPlan(dp=2), jax.devices()[:2])
        eng = Engine(XLA, params, mesh=mesh,
                     ecfg=dataclasses.replace(PAGED, n_pages=8))
        sched = Scheduler(eng, async_dispatch=async_on)
        try:
            assert sched.async_dispatch is async_on
            out = list(sched.submit(PROMPT, max_tokens=6,
                                    opts=GREEDY).tokens())
            _drain(sched)
            return out
        finally:
            sched.shutdown()

    before = METRICS.get("tpu_model_async_fallback_total",
                         '{cause="paged_dp"}')
    assert arm(True) == arm(False)
    assert METRICS.get("tpu_model_async_fallback_total",
                       '{cause="paged_dp"}') == before


def test_grammar_device_dispatch_stays_async(params):
    """cause="grammar" retired for device-table grammars: a constrained
    slot rides the double-buffered chunked dispatch (mask + automaton
    advance on device) and the fallback counter never moves."""
    from ollama_operator_tpu.ops.constrain import (
        INITIAL_STATE, JsonConstraint, advance_bytes)
    from test_constrain import EOS, PIECES, make_table
    table = make_table()
    eng = Engine(XLA, params, ecfg=dataclasses.replace(
        PAGED, max_seq_len=128))
    sched = Scheduler(eng, async_dispatch=True)
    try:
        assert sched.async_dispatch
        before = METRICS.get("tpu_model_async_fallback_total",
                             '{cause="grammar"}')
        req = sched.submit([5, 9, 2],
                           SlotOptions(temperature=0.9, seed=1,
                                       repeat_penalty=1.0),
                           max_tokens=24, eog_ids=frozenset([EOS]),
                           constraint=JsonConstraint(table))
        toks = list(req.tokens())
        assert len(toks) >= 1
        data = b"".join(PIECES[t] for t in toks)
        assert advance_bytes(INITIAL_STATE, data) is not None
        assert METRICS.get("tpu_model_async_fallback_total",
                           '{cause="grammar"}') == before
        _drain(sched)
    finally:
        sched.shutdown()


def test_grammar_host_fallback_still_counts(params, monkeypatch):
    """TPU_GRAMMAR_DEVICE=0 reverts constrained slots to the host-masked
    sync path — and the retired counter proves it is the knob, not a
    silent regression, by moving again."""
    from ollama_operator_tpu.ops.constrain import JsonConstraint
    from test_constrain import EOS, make_table
    eng = Engine(XLA, params, ecfg=dataclasses.replace(
        PAGED, max_seq_len=128))
    monkeypatch.setattr(eng, "_grammar_device", False)
    sched = Scheduler(eng, async_dispatch=True)
    try:
        assert sched.async_dispatch
        before = METRICS.get("tpu_model_async_fallback_total",
                             '{cause="grammar"}')
        req = sched.submit([5, 9, 2],
                           SlotOptions(temperature=0.9, seed=1,
                                       repeat_penalty=1.0),
                           max_tokens=12, eog_ids=frozenset([EOS]),
                           constraint=JsonConstraint(make_table()))
        assert len(list(req.tokens())) >= 1
        assert METRICS.get("tpu_model_async_fallback_total",
                           '{cause="grammar"}') > before
        _drain(sched)
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# chaos: exactly-once errors + clean accounting through restart
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_engine_step_fault_paged_async_exactly_once(params, monkeypatch):
    """CI chaos drill (ISSUE 5): engine.step fail:after=1 in paged+async.
    The first launch succeeds and its in-flight tokens are delivered; the
    second raises with a dispatch pending. Every owner gets exactly ONE
    terminal error, the supervised restart drains the quarantine, and the
    page table checks clean — then serving resumes."""
    # replay off: this drill pins the exactly-once ERROR contract (the
    # zero-error replay drill lives in test_lifecycle.py)
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng, restart_backoff=0.001, async_dispatch=True)
    try:
        assert sched.async_dispatch
        FAULTS.arm("engine.step", "fail:after=1")
        reqs = [sched.submit(PROMPT + i, max_tokens=48, opts=GREEDY)
                for i in range(2)]
        errs = 0
        for r in reqs:
            try:
                assert len(list(r.tokens())) <= 48
            except RuntimeError as e:
                assert "engine.step" in str(e)
                errs += 1
            # exactly once: nothing queued after the terminal item
            with pytest.raises(queue_mod.Empty):
                r.out.get_nowait()
        assert errs == 2                       # both owners errored
        FAULTS.disarm("engine.step")
        t1 = time.monotonic() + 5
        while sched.n_restarts < 1 and time.monotonic() < t1:
            time.sleep(0.01)
        assert sched.n_restarts >= 1 and not sched.broken
        # the restart drained everything: whole pool reclaimable
        assert eng.quarantined_pages == 0
        assert eng.free_pages == eng._pt.data_pages
        eng._pt.check()
        r2 = sched.submit(PROMPT, max_tokens=6, opts=GREEDY)
        assert len(list(r2.tokens())) == 6
        _drain(sched)
    finally:
        sched.shutdown()
