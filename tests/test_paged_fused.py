"""TPU_PAGED_FUSED A/B: the fused paged-attention pallas kernels
(interpret mode on CPU) against the gather+einsum reference path the
knob re-enables, bit-for-bit at the token level — greedy and seeded,
cold and with a radix stitch, across attention tail buckets — plus the
int4 nibble-packed KV pool riding the same A/B (both arms share one
codec, so the reference path stays a parity oracle for the lossy dtype).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.models.config import PRESETS
from ollama_operator_tpu.ops import quant_cache as QC
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.scheduler import Scheduler

BASE = PRESETS["tiny"]
INTERP = dataclasses.replace(BASE, kernels="interpret")
GREEDY = SlotOptions(temperature=0.0)
SEEDED = SlotOptions(temperature=0.9, top_k=40, seed=13)
PAGED = EngineConfig(max_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
                     min_prefill_bucket=16, paged=True, page_size=8)

PREFIX = np.arange(1, 25, dtype=np.int32)          # 24 tokens = 3 pages
SHORT = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(BASE, jax.random.key(0), jnp.float32)


def _arm(params, monkeypatch, fused, cache_dtype, warm):
    """One serving arm. Probes land in different attention tail buckets
    (8-token prompt → 16 bucket, 24-token radix prefix → 32 bucket) and
    the 8-token budgets walk generation across a bucket boundary."""
    monkeypatch.setenv("TPU_PAGED_FUSED", "1" if fused else "0")
    ecfg = dataclasses.replace(PAGED, cache_dtype=cache_dtype)
    eng = Engine(INTERP, params, ecfg=ecfg)
    sched = Scheduler(eng)
    try:
        outs = []
        if warm:
            donor = np.concatenate([PREFIX, np.array([60, 61], np.int32)])
            outs.append(list(sched.submit(donor, max_tokens=4,
                                          opts=GREEDY).tokens()))
        probes = [
            (np.concatenate([PREFIX, np.array([70], np.int32)]), GREEDY),
            (np.concatenate([PREFIX, np.array([70], np.int32)]), SEEDED),
            (SHORT, GREEDY),
            (SHORT, SEEDED),
        ]
        reqs = [sched.submit(p, max_tokens=8, opts=o) for p, o in probes]
        outs += [list(r.tokens()) for r in reqs]
        for r in reqs:
            assert r.error is None
        if warm:
            assert any(r.stats.n_reused >= 16 for r in reqs)
        return outs
    finally:
        sched.shutdown()


@pytest.mark.parametrize("warm", [False, True], ids=["cold", "radix-hit"])
@pytest.mark.parametrize("cache_dtype", [jnp.float32, jnp.int8, "int4"],
                         ids=["f32", "int8", "int4"])
def test_fused_streams_match_reference(params, monkeypatch, cache_dtype,
                                       warm):
    on = _arm(params, monkeypatch, True, cache_dtype, warm)
    off = _arm(params, monkeypatch, False, cache_dtype, warm)
    assert on == off, (cache_dtype, warm)


def test_fused_knob_routes_the_kernel(params, monkeypatch):
    """The env knob actually flips the route (guards a future refactor
    that would compare the fused path against itself)."""
    from ollama_operator_tpu.models.decoder import _paged_kernel_usable
    monkeypatch.setenv("TPU_PAGED_FUSED", "1")
    assert _paged_kernel_usable(INTERP, None, 1, INTERP.n_kv_heads, 8,
                                INTERP.head_dim)
    monkeypatch.setenv("TPU_PAGED_FUSED", "0")
    assert not _paged_kernel_usable(INTERP, None, 1, INTERP.n_kv_heads, 8,
                                    INTERP.head_dim)


# --- int4 KV pool ------------------------------------------------------------

def test_quantize_kv4_roundtrip_bound():
    """Dequantised int4 codes land within half a step (scale/2) of the
    source, and the codes stay in the nibble-safe [-7, 7] band."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 4, 16, 8)), jnp.float32)
    q, s = QC.quantize_kv4(x)
    assert int(jnp.max(jnp.abs(q))) <= 7
    back = q.astype(jnp.float32) * s[..., None]
    err = np.abs(np.asarray(back - x))
    bound = np.asarray(s)[..., None] * 0.51 + 1e-7
    assert (err <= bound).all()


def test_pack_unpack_kv4_exact():
    rng = np.random.default_rng(6)
    codes = jnp.asarray(rng.integers(-7, 8, (3, 2, 10, 4)), jnp.int8)
    packed = QC.pack_kv4(codes)
    assert packed.shape == (3, 2, 5, 4)
    np.testing.assert_array_equal(np.asarray(QC.unpack_kv4(packed)),
                                  np.asarray(codes))


def test_attend_hf_q4_close_to_dense():
    from ollama_operator_tpu.ops import attention as A
    rng = np.random.default_rng(7)
    B, T, S, H, KvH, hd = 2, 1, 32, 8, 2, 16
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), jnp.float32) * 0.3
    k = jnp.asarray(rng.standard_normal((B, KvH, S, hd)), jnp.float32) * 0.3
    v = jnp.asarray(rng.standard_normal((B, KvH, S, hd)), jnp.float32) * 0.3
    mask = jnp.broadcast_to(A.causal_mask(T, S, 20), (B, 1, T, S))
    ref = A.attend_hf(q, k, v, mask, hd ** -0.5)
    kq, ks = QC.quantize_kv4(k)
    vq, vs = QC.quantize_kv4(v)
    got = QC.attend_hf_q4(q, {"q4": QC.pack_kv4(kq), "s": ks},
                          {"q4": QC.pack_kv4(vq), "s": vs},
                          mask, hd ** -0.5)
    # 4-bit KV: looser than int8 but the attention output stays close
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=0.25, atol=0.1)


def test_int4_requires_paged(params):
    with pytest.raises(ValueError):
        Engine(BASE, params, ecfg=EngineConfig(
            max_slots=2, max_seq_len=64, cache_dtype="int4",
            min_prefill_bucket=16))


def test_int4_engine_end_to_end(params):
    """int4 paged engine decodes through bucket crossings; the pool's
    code arrays are half-width (two positions per byte)."""
    ecfg = dataclasses.replace(PAGED, cache_dtype="int4")
    eng = Engine(BASE, params, ecfg=ecfg)
    t0 = eng.admit(0, SHORT, GREEDY)
    toks = [t0]
    for _ in range(4):
        toks.extend(int(x) for x in eng.decode_n(4)[:, 0])
    assert len(toks) == 17 and all(0 <= t < BASE.vocab_size for t in toks)
    k_pool = eng.k_cache[0] if isinstance(eng.k_cache, list) else eng.k_cache
    assert QC.pool_bits(k_pool) == 4
    # greedy first token agrees with the f32 engine (prefill is unquantized)
    eng2 = Engine(BASE, params, ecfg=PAGED)
    assert t0 == eng2.admit(0, SHORT, GREEDY)
