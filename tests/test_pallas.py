"""Parity tests: pallas flash kernels vs the pure-JAX attention reference.

Run through the pallas interpreter on the CPU test mesh (conftest.py), so
the exact kernel code that runs compiled on TPU is exercised here —
SURVEY.md §4's "real semantics, fake hardware" tier for the kernel layer.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models.config import PRESETS
from ollama_operator_tpu.ops.attention import attend, attend_hf, causal_mask
from ollama_operator_tpu.ops.pallas import decode_attention, flash_prefill


def _rand_qkv(key, B, T, S, H, KvH, hd, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, T, H, hd), dtype)
    k = jax.random.normal(kk, (B, S, KvH, hd), dtype)
    v = jax.random.normal(kv, (B, S, KvH, hd), dtype)
    return q, k, v


@pytest.mark.parametrize("H,KvH", [(8, 8), (8, 2), (4, 1)])
def test_flash_prefill_matches_reference(H, KvH):
    B, T, hd = 2, 128, 64
    q, k, v = _rand_qkv(jax.random.key(0), B, T, T, H, KvH, hd)
    scale = hd ** -0.5
    out = flash_prefill(q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
                        scale, interpret=True)
    assert out is not None
    mask = jnp.broadcast_to(causal_mask(T, T, 0), (B, 1, T, T))
    ref = attend(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_flash_prefill_sliding_window_and_softcap():
    B, T, H, KvH, hd = 1, 128, 4, 2, 32
    q, k, v = _rand_qkv(jax.random.key(1), B, T, T, H, KvH, hd)
    scale = hd ** -0.5
    for window, cap in [(32, 0.0), (0, 8.0), (48, 4.0)]:
        out = flash_prefill(q, k.transpose(0, 2, 1, 3),
                            v.transpose(0, 2, 1, 3), scale, softcap=cap,
                            sliding_window=window, interpret=True)
        mask = jnp.broadcast_to(
            causal_mask(T, T, 0, sliding_window=window), (B, 1, T, T))
        ref = attend(q, k, v, mask, scale, softcap=cap)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


def test_flash_prefill_bf16_tolerance():
    B, T, H, KvH, hd = 2, 64, 8, 4, 64
    q, k, v = _rand_qkv(jax.random.key(2), B, T, T, H, KvH, hd, jnp.bfloat16)
    scale = hd ** -0.5
    out = flash_prefill(q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
                        scale, interpret=True)
    mask = jnp.broadcast_to(causal_mask(T, T, 0), (B, 1, T, T))
    ref = attend(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("H,KvH", [(8, 2), (28, 4)])  # 28/4: G=7, padded
def test_decode_matches_reference(H, KvH):
    B, S, hd = 4, 128, 64
    q, k, v = _rand_qkv(jax.random.key(3), B, 1, S, H, KvH, hd)
    scale = hd ** -0.5
    q_pos = jnp.array([0, 5, 63, 127], jnp.int32)
    out = decode_attention(q, k.transpose(0, 2, 1, 3),
                           v.transpose(0, 2, 1, 3), q_pos, scale,
                           interpret=True)
    assert out is not None
    # reference semantics: keys j <= q_pos[b]
    k_idx = jnp.arange(S)[None, :]
    mask = jnp.where(k_idx <= q_pos[:, None], 0.0, -1e30)[:, None, None, :]
    ref = attend(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_mha_decode_matches_reference():
    """Head-tiled MHA decode kernel (grid (B, H/8, nk) — round-2 VERDICT
    weak #3): must match the einsum reference like the GQA kernel does."""
    from ollama_operator_tpu.ops.pallas import mha_decode_attention
    B, S, H, hd = 4, 128, 16, 64
    q, k, v = _rand_qkv(jax.random.key(11), B, 1, S, H, H, hd)
    scale = hd ** -0.5
    q_pos = jnp.array([0, 5, 63, 127], jnp.int32)
    out = mha_decode_attention(q, k.transpose(0, 2, 1, 3),
                               v.transpose(0, 2, 1, 3), q_pos, scale,
                               interpret=True)
    assert out is not None
    k_idx = jnp.arange(S)[None, :]
    mask = jnp.where(k_idx <= q_pos[:, None], 0.0, -1e30)[:, None, None, :]
    ref = attend(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_mha_decode_sliding_window_and_bails():
    from ollama_operator_tpu.ops.pallas import mha_decode_attention
    B, S, H, hd = 2, 128, 8, 32
    q, k, v = _rand_qkv(jax.random.key(12), B, 1, S, H, H, hd)
    scale = hd ** -0.5
    q_pos = jnp.array([40, 127], jnp.int32)
    window = 16
    out = mha_decode_attention(q, k.transpose(0, 2, 1, 3),
                               v.transpose(0, 2, 1, 3), q_pos, scale,
                               sliding_window=window, interpret=True)
    k_idx = jnp.arange(S)[None, :]
    ok = (k_idx <= q_pos[:, None]) & (k_idx > q_pos[:, None] - window)
    mask = jnp.where(ok, 0.0, -1e30)[:, None, None, :]
    ref = attend(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)
    # GQA shapes (KvH < H) and non-multiple-of-8 head counts bail to None
    qg, kg, vg = _rand_qkv(jax.random.key(13), 2, 1, 128, 8, 2, 32)
    assert mha_decode_attention(qg, kg.transpose(0, 2, 1, 3),
                                vg.transpose(0, 2, 1, 3), q_pos, scale,
                                interpret=True) is None


def test_mha_kernel_env_routes_engine_decode():
    """TPU_MHA_KERNEL=1 + interpret kernels: the engine's decode path
    must route MHA through the head-tiled kernel and keep greedy parity
    with the einsum path."""
    import dataclasses as dc
    import os

    from ollama_operator_tpu.models import config as cfglib, decoder
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    # tiny is GQA (4:2); make an MHA variant
    cfg = dc.replace(cfglib.PRESETS["tiny"], n_kv_heads=4)
    params = decoder.init_params(cfg, jax.random.key(5), jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_seq_len=64,
                        cache_dtype=jnp.float32, min_prefill_bucket=16)
    prompt = np.arange(1, 11, dtype=np.int32)
    greedy = SlotOptions(temperature=0.0)

    def run(kernels):
        eng = Engine(dc.replace(cfg, kernels=kernels), params, ecfg=ecfg)
        seq = [eng.admit(0, prompt, greedy)]
        seq.extend(int(t[0]) for t in
                   (eng.decode() for _ in range(5)))
        return seq

    ref = run("xla")
    os.environ["TPU_MHA_KERNEL"] = "1"
    try:
        got = run("interpret")
    finally:
        del os.environ["TPU_MHA_KERNEL"]
    assert got == ref


def test_decode_sliding_window():
    B, S, H, KvH, hd = 2, 128, 4, 2, 32
    q, k, v = _rand_qkv(jax.random.key(4), B, 1, S, H, KvH, hd)
    scale = hd ** -0.5
    q_pos = jnp.array([40, 127], jnp.int32)
    window = 16
    out = decode_attention(q, k.transpose(0, 2, 1, 3),
                           v.transpose(0, 2, 1, 3), q_pos, scale,
                           sliding_window=window, interpret=True)
    k_idx = jnp.arange(S)[None, :]
    ok = (k_idx <= q_pos[:, None]) & (k_idx > q_pos[:, None] - window)
    mask = jnp.where(ok, 0.0, -1e30)[:, None, None, :]
    ref = attend(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_untileable_shapes_fall_back():
    # T=100 has no block divisor in the table → kernel declines, caller
    # falls back to the XLA path.
    q = jnp.zeros((1, 100, 4, 32))
    k = v = jnp.zeros((1, 2, 100, 32))
    assert flash_prefill(q, k, v, 1.0, interpret=True) is None
    # head_dim not a 16-multiple → declined when compiled (Mosaic handles
    # 16-multiples like phi's 80 fine — verified on v5e), allowed interpreted
    q2 = jnp.zeros((1, 128, 4, 72))
    k2 = v2 = jnp.zeros((1, 2, 128, 72))
    assert flash_prefill(q2, k2, v2, 1.0, interpret=False) is None


def test_attend_hf_matches_attend():
    B, T, S, H, KvH, hd = 2, 4, 32, 8, 2, 16
    q, k, v = _rand_qkv(jax.random.key(7), B, T, S, H, KvH, hd)
    lengths = jnp.array([10, 32], jnp.int32)
    k_idx = jnp.arange(S)[None, :]
    mask = jnp.where(k_idx < lengths[:, None], 0.0, -1e30)[:, None, None, :]
    ref = attend(q, k, v, mask, 0.25, softcap=5.0)
    out = attend_hf(q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
                    mask, 0.25, softcap=5.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_engine_tokens_identical_across_kernel_paths():
    """Greedy decode through the real Engine must produce the same tokens
    with interpreted pallas kernels as with the XLA path."""
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    from ollama_operator_tpu.models import decoder

    base = PRESETS["tiny"]
    params = decoder.init_params(base, jax.random.key(0), jnp.float32)
    prompt = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
    opts = SlotOptions(temperature=0.0)  # greedy → deterministic

    toks = {}
    for mode in ("xla", "interpret"):
        cfg = dataclasses.replace(base, kernels=mode)
        eng = Engine(cfg, params,
                     ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                       min_prefill_bucket=16))
        first = eng.admit(0, prompt, opts)
        seq = [first]
        for _ in range(4):
            seq.append(int(eng.decode()[0]))
        toks[mode] = seq
    assert toks["xla"] == toks["interpret"], toks


@pytest.mark.parametrize("plan_kw", [dict(tp=2), dict(dp=2, tp=2)])
def test_engine_mesh_shardmap_kernels_match_single_device(plan_kw):
    """Round-1 VERDICT weak #2: the engine used to force kernels="xla" on
    any >1-device mesh. Now the pallas kernels run inside a dp/tp-manual
    shard_map — greedy tokens on a real mesh with interpreted kernels must
    equal the single-device XLA path exactly."""
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    from ollama_operator_tpu.models import decoder

    base = PRESETS["tiny"]
    params = decoder.init_params(base, jax.random.key(0), jnp.float32)
    prompt = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
    opts = SlotOptions(temperature=0.0)
    ecfg = EngineConfig(max_slots=4, max_seq_len=64,
                        cache_dtype=jnp.float32, min_prefill_bucket=16)

    def run(cfg, mesh):
        eng = Engine(cfg, params, mesh=mesh, ecfg=ecfg)
        seq = [eng.admit(0, prompt, opts), eng.admit(1, prompt[:5], opts)]
        for _ in range(4):
            t = eng.decode()
            seq.extend([int(t[0]), int(t[1])])
        return seq

    ref = run(dataclasses.replace(base, kernels="xla"), None)
    mesh = make_mesh(MeshPlan(**plan_kw))
    got = run(dataclasses.replace(base, kernels="interpret"), mesh)
    assert got == ref, (got, ref)


def test_dispatch_shardmap_matches_reference_direct():
    """chunk_attention / cached_attention with a mesh + interpret kernels
    vs the einsum reference, exact shardable shapes (H and KvH divide tp,
    B divides dp)."""
    from ollama_operator_tpu.models.config import PRESETS as _P
    from ollama_operator_tpu.ops.attention import (cached_attention,
                                                   chunk_attention)
    from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
    import dataclasses as dc

    cfg = dc.replace(_P["tiny"], kernels="interpret")
    B, T, H, KvH, hd = 2, 32, 4, 2, 16
    key = jax.random.key(7)
    q, k, v = _rand_qkv(key, B, T, T, H, KvH, hd)
    k_hf = k.transpose(0, 2, 1, 3)
    v_hf = v.transpose(0, 2, 1, 3)
    mask = causal_mask(T, T, 0)
    ref = attend_hf(q, k_hf, v_hf, mask, 0.25)
    mesh = make_mesh(MeshPlan(dp=2, tp=2))
    out = jax.jit(lambda q, k, v: chunk_attention(
        cfg, q, k, v, mask, 0.25, mesh=mesh))(q, k_hf, v_hf)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)

    # decode: T=1 queries against a padded cache with per-slot lengths
    S = 64
    qd = jax.random.normal(jax.random.key(8), (B, 1, H, hd), jnp.float32)
    kc = jax.random.normal(jax.random.key(9), (B, KvH, S, hd), jnp.float32)
    vc = jax.random.normal(jax.random.key(10), (B, KvH, S, hd), jnp.float32)
    q_pos = jnp.array([[5], [33]], jnp.int32)
    k_pos = jnp.arange(S, dtype=jnp.int32)[None, None, :]
    ok = k_pos <= q_pos[:, :, None]
    maskd = jnp.where(ok, 0.0, -1e30).astype(jnp.float32)[:, None, :, :]
    refd = attend_hf(qd, kc, vc, maskd, 0.25)
    outd = jax.jit(lambda q, k, v, p: cached_attention(
        cfg, q, k, v, maskd, p, 0.25, mesh=mesh))(qd, kc, vc, q_pos)
    np.testing.assert_allclose(np.asarray(outd), np.asarray(refd),
                               rtol=1e-5, atol=1e-5)
