"""Cross-implementation parity: our GGUF→transcode→JAX pipeline vs
HuggingFace transformers' LlamaForCausalLM (torch CPU) on identical
weights, plus spec-derived dequant goldens.

SURVEY §7 risk 1 / round-1 weak #10: transcode/rope/layout conventions
were proven only against self-built fixtures. With zero network egress no
real llama GGUF exists in this image, so the strongest independent anchor
is transformers itself — the ecosystem-canonical llama implementation the
GGUF converters start from. The test-side exporter applies llama.cpp's
documented q/k interleave permutation (convert_hf_to_gguf.py's
``LlamaModel.permute``), so our transcoder's unpermute is validated
against the official conversion, not against itself.

The dequant goldens hand-derive expected values from the ggml block-format
specs with crafted byte patterns — they pin the ABSOLUTE convention, where
the python↔C++ agreement tests (test_native.py) only pin consistency.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ollama_operator_tpu.gguf import dequant as DQ
from ollama_operator_tpu.gguf import reader as R
from ollama_operator_tpu.gguf import writer as W
from ollama_operator_tpu.gguf.transcode import load_model as transcode_load
from ollama_operator_tpu.models import decoder

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


# ---------------------------------------------------------------------------
# HF → GGUF export (test-side, following convert_hf_to_gguf.py conventions)
# ---------------------------------------------------------------------------

def hf_permute(w: np.ndarray, n_head: int) -> np.ndarray:
    """llama.cpp's LlamaModel.permute: HF half-split rope layout → the
    interleaved (Meta) layout GGUF stores. w [out, in]."""
    out, inn = w.shape
    return (w.reshape(n_head, 2, out // n_head // 2, inn)
             .swapaxes(1, 2).reshape(out, inn))


def export_hf_to_gguf(path: str, model, hf_cfg, quant=None):
    """Export a transformers LlamaForCausalLM state dict as a
    llama-arch GGUF (f32, or q8_0 for the 2D matmul weights)."""
    sd = {k: v.detach().cpu().numpy().astype(np.float32)
          for k, v in model.state_dict().items()}
    H, KvH = hf_cfg.num_attention_heads, hf_cfg.num_key_value_heads
    w = W.GGUFWriter(path)
    w.add_meta("general.architecture", "llama")
    w.add_meta("llama.block_count", hf_cfg.num_hidden_layers)
    w.add_meta("llama.embedding_length", hf_cfg.hidden_size)
    w.add_meta("llama.attention.head_count", H)
    w.add_meta("llama.attention.head_count_kv", KvH)
    w.add_meta("llama.attention.key_length",
               hf_cfg.hidden_size // H)
    w.add_meta("llama.feed_forward_length", hf_cfg.intermediate_size)
    w.add_meta("llama.context_length", hf_cfg.max_position_embeddings)
    w.add_meta("llama.rope.freq_base", float(hf_cfg.rope_theta))
    w.add_meta("llama.attention.layer_norm_rms_epsilon",
               float(hf_cfg.rms_norm_eps))
    V = hf_cfg.vocab_size
    w.add_meta("tokenizer.ggml.model", "llama")
    w.add_meta("tokenizer.ggml.tokens", [f"t{i}" for i in range(V)])
    w.add_meta("tokenizer.ggml.scores", [0.0] * V)
    w.add_meta("tokenizer.ggml.token_type", [1] * V)

    def put(name, arr, quantizable=True):
        arr = np.ascontiguousarray(arr, np.float32)
        if quant == "q8_0" and quantizable and arr.ndim == 2:
            w.add_tensor_raw(name, arr.shape, R.GGML_Q8_0,
                             W.quantize_q8_0(arr))
        else:
            w.add_tensor_f32(name, arr)

    put("token_embd.weight", sd["model.embed_tokens.weight"],
        quantizable=False)   # embedding gather stays exact
    put("output_norm.weight", sd["model.norm.weight"])
    put("output.weight", sd["lm_head.weight"])
    for i in range(hf_cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        put(b + "attn_norm.weight", sd[p + "input_layernorm.weight"])
        put(b + "attn_q.weight",
            hf_permute(sd[p + "self_attn.q_proj.weight"], H))
        put(b + "attn_k.weight",
            hf_permute(sd[p + "self_attn.k_proj.weight"], KvH))
        put(b + "attn_v.weight", sd[p + "self_attn.v_proj.weight"])
        put(b + "attn_output.weight", sd[p + "self_attn.o_proj.weight"])
        put(b + "ffn_norm.weight",
            sd[p + "post_attention_layernorm.weight"])
        put(b + "ffn_gate.weight", sd[p + "mlp.gate_proj.weight"])
        put(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        put(b + "ffn_down.weight", sd[p + "mlp.down_proj.weight"])
    w.write()


def _hf_model():
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, tie_word_embeddings=False)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg


IDS = [3, 1, 4, 1, 5, 9, 2, 6, 53, 58, 97, 93]


def _our_logits(gguf_path):
    cfg, params, _ = transcode_load(gguf_path, dtype=np.float32)
    params = jax.tree.map(jnp.asarray, params)
    tokens = jnp.asarray(np.array(IDS, np.int32)[None])
    logits, _, _ = decoder.prefill_chunk(params, cfg, tokens)
    return np.asarray(logits[0], np.float64)


def test_logits_match_transformers_f32(tmp_path):
    model, hf_cfg = _hf_model()
    with torch.no_grad():
        ref = model(torch.tensor([IDS])).logits[0].numpy().astype(np.float64)
    path = str(tmp_path / "hf.gguf")
    export_hf_to_gguf(path, model, hf_cfg)
    got = _our_logits(path)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    # the match must be meaningful, not all-zeros
    assert np.abs(ref).max() > 0.1


def test_greedy_tokens_match_transformers_q8_0(tmp_path):
    """q8_0 weights through the real dequant path: quantization noise is
    identical on both sides only for OUR pipeline, so compare greedy
    argmax tokens against f32-transformers with the quantized logits —
    they must agree at every position where the f32 margin dominates the
    quantization error."""
    model, hf_cfg = _hf_model()
    with torch.no_grad():
        ref_logits = model(torch.tensor([IDS])).logits[0].numpy()
    path = str(tmp_path / "hf_q8.gguf")
    export_hf_to_gguf(path, model, hf_cfg, quant="q8_0")
    got = _our_logits(path)
    err = np.abs(got - ref_logits).max()
    top2 = np.sort(ref_logits, axis=-1)
    margin = top2[:, -1] - top2[:, -2]
    decisive = margin > 4 * err
    assert decisive.any()
    np.testing.assert_array_equal(got.argmax(-1)[decisive],
                                  ref_logits.argmax(-1)[decisive])


# ---------------------------------------------------------------------------
# spec-derived dequant goldens (hand-crafted blocks, hand-computed values)
# ---------------------------------------------------------------------------

def _f16_bytes(x: float) -> bytes:
    return np.float16(x).tobytes()


def test_q8_0_golden():
    # block: f16 d, 32 × int8. value[i] = d * q[i]
    qs = np.arange(-16, 16, dtype=np.int8)
    raw = np.frombuffer(_f16_bytes(0.5) + qs.tobytes(), np.uint8)
    got = DQ.dq_q8_0(raw)
    np.testing.assert_allclose(got, 0.5 * qs.astype(np.float32), atol=1e-3)


def test_q4_0_golden():
    # block: f16 d, 16 bytes of nibbles. weight i<16 = low nibble of
    # byte i, weight i>=16 = high nibble of byte i-16; value = d*(q - 8)
    lo = np.arange(16, dtype=np.uint8)          # weights 0..15 = 0..15
    hi = np.full(16, 0xA, np.uint8)             # weights 16..31 = 10
    qs = (lo | (hi << 4)).astype(np.uint8)
    raw = np.frombuffer(_f16_bytes(0.25) + qs.tobytes(), np.uint8)
    got = DQ.dq_q4_0(raw)
    exp = 0.25 * (np.concatenate([np.arange(16), np.full(16, 10)]) - 8.0)
    np.testing.assert_allclose(got, exp.astype(np.float32), atol=1e-3)


def test_q4_k_golden():
    # super-block of 256: f16 d, f16 dmin, 12 bytes of 6-bit scales/mins,
    # 128 nibble bytes. With scale bytes [1]*4 + [0]*4 + [1]*4 every
    # sub-block gets sc=1, m=0 (llama.cpp get_scale_min_k4), so
    # value = d * nibble. Nibbles: each 64-weight group j reads 32 bytes;
    # weights j*64+i (i<32) = low nibbles, +32..63 = high nibbles.
    scales = bytes([1, 1, 1, 1, 0, 0, 0, 0, 1, 1, 1, 1])
    nib = np.tile(np.arange(16, dtype=np.uint8), 2)   # 32 bytes per group
    qs = np.tile(nib | (nib << 4), 4)                 # 128 bytes
    raw = np.frombuffer(_f16_bytes(0.5) + _f16_bytes(0.0) + scales
                        + qs.tobytes(), np.uint8)
    got = DQ.dq_q4_k(raw)
    group = np.concatenate([np.tile(np.arange(16), 2)] * 2)  # lo then hi
    exp = 0.5 * np.tile(group, 4).astype(np.float32)
    np.testing.assert_allclose(got, exp, atol=1e-3)


def test_q6_k_golden():
    # super-block of 256: ql 128 B, qh 64 B, 16 int8 scales, f16 d.
    # With qh = 0 the 6-bit q is just the 4-bit nibble; value =
    # d * sc[i//16] * (q - 32). Scales alternate 1, 2.
    nib = np.tile(np.arange(16, dtype=np.uint8), 4)   # 64 bytes per half
    ql = np.tile(nib | (nib << 4), 2)                 # 128 bytes
    qh = np.zeros(64, np.uint8)
    scales = np.tile(np.array([1, 2], np.int8), 8)    # 16 sub-blocks
    raw = np.frombuffer(ql.tobytes() + qh.tobytes() + scales.tobytes()
                        + _f16_bytes(1.0), np.uint8)
    got = DQ.dq_q6_k(raw)
    # layout per 128-weight half: weights 0..31 = low nibbles of bytes
    # 0..31, 32..63 = low nibbles of 32..63, 64..95 = high of 0..31,
    # 96..127 = high of 32..63 (qh contributes bits 4..5, zero here)
    lo = np.concatenate([np.tile(np.arange(16), 2)] * 2)     # 64 lows
    half = np.concatenate([lo, lo])                          # + 64 highs
    q = np.concatenate([half, half]).astype(np.float32)
    sc = np.repeat(scales.astype(np.float32), 16)
    exp = sc * (q - 32.0)
    np.testing.assert_allclose(got, exp, atol=1e-3)


# ---------------------------------------------------------------------------
# committed golden regression fixture
# ---------------------------------------------------------------------------

# Blessed on the round-2 CPU CI environment from the deterministic
# (torch seed 0) q8_0 fixture below. Any transcode/dequant/rope/engine
# change that alters serving semantics — or an XLA numeric change big
# enough to flip a greedy argmax — trips this; re-bless consciously with
# hack/gen_golden reasoning, never mechanically.
GOLDEN_TOKENS = [134, 190, 139, 177, 98, 34, 29, 93, 134, 102, 28, 98]
GOLDEN_LOGITS_8 = [-0.13376, 0.02682, 0.14595, -0.04723, -0.05149,
                   -0.20087, -0.18322, -0.15094]


def test_golden_tokens_regression(tmp_path):
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    model, hf_cfg = _hf_model()
    path = str(tmp_path / "golden.gguf")
    export_hf_to_gguf(path, model, hf_cfg, quant="q8_0")
    cfg, params, _ = transcode_load(path, dtype=np.float32)
    params = jax.tree.map(jnp.asarray, params)
    lg, _, _ = decoder.prefill_chunk(
        params, cfg, jnp.asarray(np.array(IDS, np.int32)[None]))
    np.testing.assert_allclose(np.asarray(lg[0, -1, :8]), GOLDEN_LOGITS_8,
                               atol=1e-3)
    eng = Engine(cfg, params,
                 ecfg=EngineConfig(max_slots=1, max_seq_len=64,
                                   cache_dtype=jnp.float32,
                                   min_prefill_bucket=16))
    g = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    seq = [eng.admit(0, np.array(IDS, np.int32), g)]
    for _ in range(11):
        seq.append(int(eng.decode()[0]))
    assert seq == GOLDEN_TOKENS, seq
