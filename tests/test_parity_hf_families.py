"""Cross-implementation parity for every served model family.

Extends tests/test_parity_hf.py's anchor (our GGUF→transcode→JAX pipeline
vs transformers on identical weights) beyond llama: mistral (sliding
window), qwen2 (attention bias, no rope permute), gemma (GeGLU, +1 norm
offset, embedding scaling, tied head, wide head_dim), phi-2 (parallel
block, partial rotary, LayerNorm, biases everywhere). Each exporter
follows the family's llama.cpp conversion conventions (permute only for
the llama family; everything else NEOX-layout), so the per-arch transcode
paths are validated against the ecosystem-canonical implementations —
SURVEY §7 risk 1 across ALL families, not just llama.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ollama_operator_tpu.gguf import writer as W
from ollama_operator_tpu.gguf.transcode import load_model as transcode_load
from ollama_operator_tpu.models import decoder

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from test_parity_hf import hf_permute  # noqa: E402

IDS = [3, 1, 4, 1, 5, 9, 2, 6, 53, 58, 97, 93]


def _base_meta(w, arch, hf_cfg, head_dim=None):
    H = hf_cfg.num_attention_heads
    w.add_meta("general.architecture", arch)
    w.add_meta(f"{arch}.block_count", hf_cfg.num_hidden_layers)
    w.add_meta(f"{arch}.embedding_length", hf_cfg.hidden_size)
    w.add_meta(f"{arch}.attention.head_count", H)
    w.add_meta(f"{arch}.attention.head_count_kv",
               getattr(hf_cfg, "num_key_value_heads", H))
    w.add_meta(f"{arch}.attention.key_length",
               head_dim or hf_cfg.hidden_size // H)
    w.add_meta(f"{arch}.feed_forward_length", hf_cfg.intermediate_size)
    w.add_meta(f"{arch}.context_length", hf_cfg.max_position_embeddings)
    w.add_meta(f"{arch}.rope.freq_base", float(hf_cfg.rope_theta))
    V = hf_cfg.vocab_size
    w.add_meta("tokenizer.ggml.model", "llama")
    w.add_meta("tokenizer.ggml.tokens", [f"t{i}" for i in range(V)])
    w.add_meta("tokenizer.ggml.scores", [0.0] * V)
    w.add_meta("tokenizer.ggml.token_type", [1] * V)


def _sd(model):
    return {k: v.detach().cpu().numpy().astype(np.float32)
            for k, v in model.state_dict().items()}


def _our_logits(path):
    cfg, params, _ = transcode_load(path, dtype=np.float32)
    params = jax.tree.map(jnp.asarray, params)
    logits, _, _ = decoder.prefill_chunk(
        params, cfg, jnp.asarray(np.array(IDS, np.int32)[None]))
    return np.asarray(logits[0], np.float64)


def _ref_logits(model):
    with torch.no_grad():
        return model(torch.tensor([IDS])).logits[0].numpy() \
            .astype(np.float64)


def _check(path, model, rtol=3e-4, atol=3e-4):
    ref = _ref_logits(model)
    got = _our_logits(path)
    assert np.abs(ref).max() > 0.05       # a meaningful comparison
    np.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)


# ---------------------------------------------------------------------------

def test_mistral_sliding_window(tmp_path):
    cfg = transformers.MistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0, sliding_window=6,
        attn_implementation="eager")
    torch.manual_seed(1)
    model = transformers.MistralForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "mistral.gguf"))
    _base_meta(w, "llama", cfg)   # mistral ships as arch "llama" in GGUF
    w.add_meta("llama.attention.sliding_window", cfg.sliding_window)
    w.add_meta("llama.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    H, KvH = cfg.num_attention_heads, cfg.num_key_value_heads
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        w.add_tensor_f32(b + "attn_q.weight",
                         hf_permute(sd[p + "self_attn.q_proj.weight"], H))
        w.add_tensor_f32(b + "attn_k.weight",
                         hf_permute(sd[p + "self_attn.k_proj.weight"], KvH))
        w.add_tensor_f32(b + "attn_v.weight",
                         sd[p + "self_attn.v_proj.weight"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()
    _check(str(tmp_path / "mistral.gguf"), model)


def test_qwen2_attention_bias_no_permute(tmp_path):
    cfg = transformers.Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        attn_implementation="eager")
    torch.manual_seed(2)
    model = transformers.Qwen2ForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "qwen2.gguf"))
    _base_meta(w, "qwen2", cfg)
    w.add_meta("qwen2.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        # qwen2 is NEOX layout: llama.cpp does NOT permute q/k
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
            w.add_tensor_f32(b + dst + ".bias",
                             sd[p + f"self_attn.{src}.bias"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()
    _check(str(tmp_path / "qwen2.gguf"), model)


def test_gemma_geglu_norm_offset_tied_head(tmp_path):
    cfg = transformers.GemmaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=128, rope_theta=10000.0,
        hidden_act="gelu_pytorch_tanh", attn_implementation="eager")
    torch.manual_seed(3)
    model = transformers.GemmaForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "gemma.gguf"))
    _base_meta(w, "gemma", cfg, head_dim=cfg.head_dim)
    w.add_meta("gemma.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    # gemma norms ship as stored (HF keeps w with (1+w) semantics); no
    # output.weight — the head ties to the embedding
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()
    _check(str(tmp_path / "gemma.gguf"), model)


def test_phi2_parallel_block_partial_rotary(tmp_path):
    cfg = transformers.PhiConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=128, rope_theta=10000.0,
        partial_rotary_factor=0.5, layer_norm_eps=1e-5,
        attn_implementation="eager")
    torch.manual_seed(4)
    model = transformers.PhiForCausalLM(cfg).eval()
    sd = _sd(model)
    hd = cfg.hidden_size // cfg.num_attention_heads
    w = W.GGUFWriter(str(tmp_path / "phi2.gguf"))
    _base_meta(w, "phi2", cfg)
    w.add_meta("phi2.attention.layer_norm_epsilon",
               float(cfg.layer_norm_eps))
    w.add_meta("phi2.rope.dimension_count",
               int(hd * cfg.partial_rotary_factor))
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.final_layernorm.weight"])
    w.add_tensor_f32("output_norm.bias", sd["model.final_layernorm.bias"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    w.add_tensor_f32("output.bias", sd["lm_head.bias"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        w.add_tensor_f32(b + "attn_norm.bias",
                         sd[p + "input_layernorm.bias"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
            w.add_tensor_f32(b + dst + ".bias",
                             sd[p + f"self_attn.{src}.bias"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.dense.weight"])
        w.add_tensor_f32(b + "attn_output.bias",
                         sd[p + "self_attn.dense.bias"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.fc1.weight"])
        w.add_tensor_f32(b + "ffn_up.bias", sd[p + "mlp.fc1.bias"])
        w.add_tensor_f32(b + "ffn_down.weight", sd[p + "mlp.fc2.weight"])
        w.add_tensor_f32(b + "ffn_down.bias", sd[p + "mlp.fc2.bias"])
    w.write()
    _check(str(tmp_path / "phi2.gguf"), model)


def _export_gemma2(path, model, cfg):
    sd = _sd(model)
    w = W.GGUFWriter(path)
    _base_meta(w, "gemma2", cfg, head_dim=cfg.head_dim)
    w.add_meta("gemma2.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_meta("gemma2.attention.sliding_window", cfg.sliding_window)
    w.add_meta("gemma2.attn_logit_softcapping",
               float(cfg.attn_logit_softcapping))
    w.add_meta("gemma2.final_logit_softcapping",
               float(cfg.final_logit_softcapping))
    w.add_meta("gemma2.attention.query_pre_attn_scalar",
               float(cfg.query_pre_attn_scalar))
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "post_attention_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "pre_feedforward_layernorm.weight"])
        w.add_tensor_f32(b + "post_ffw_norm.weight",
                         sd[p + "post_feedforward_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()


def test_gemma2_sandwich_norms_softcaps_alternating_window(tmp_path):
    """gemma2: post-attn/post-ffw sandwich norms, attn + final logit
    soft-capping, query_pre_attn_scalar score scale, and alternating
    sliding/global layers — every piece validated at once against
    transformers' Gemma2ForCausalLM."""
    cfg = transformers.Gemma2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=128, rope_theta=10000.0,
        sliding_window=6, query_pre_attn_scalar=24.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        hidden_act="gelu_pytorch_tanh", attn_implementation="eager")
    torch.manual_seed(5)
    model = transformers.Gemma2ForCausalLM(cfg).eval()
    path = str(tmp_path / "gemma2.gguf")
    _export_gemma2(path, model, cfg)
    _check(path, model)


def test_gemma2_greedy_decode_matches_transformers(tmp_path):
    """The CACHED decode path (per-layer alternating windows against the
    slot KV cache) must continue exactly like transformers' greedy
    generate — prefill parity alone wouldn't catch a wrong per-layer
    window in forward_with_cache."""
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    cfg = transformers.Gemma2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=128, rope_theta=10000.0,
        sliding_window=6, query_pre_attn_scalar=24.0,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        hidden_act="gelu_pytorch_tanh", attn_implementation="eager")
    torch.manual_seed(5)
    model = transformers.Gemma2ForCausalLM(cfg).eval()
    with torch.no_grad():
        ref = model.generate(torch.tensor([IDS]), max_new_tokens=6,
                             do_sample=False)[0, len(IDS):].tolist()

    path = str(tmp_path / "g2.gguf")
    _export_gemma2(path, model, cfg)
    mcfg, params, _ = transcode_load(path, dtype=np.float32)
    params = jax.tree.map(jnp.asarray, params)
    eng = Engine(mcfg, params,
                 ecfg=EngineConfig(max_slots=1, max_seq_len=64,
                                   cache_dtype=jnp.float32,
                                   min_prefill_bucket=16))
    g = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    got = [eng.admit(0, np.array(IDS, np.int32), g)]
    for _ in range(5):
        got.append(int(eng.decode()[0]))
    assert got == ref, (got, ref)


def test_qwen3_qk_norm(tmp_path):
    """qwen3: per-head RMS norms on q/k (no qkv bias, NEOX layout)."""
    cfg = transformers.Qwen3Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=128, rope_theta=10000.0,
        attn_implementation="eager")
    torch.manual_seed(6)
    model = transformers.Qwen3ForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "qwen3.gguf"))
    _base_meta(w, "qwen3", cfg, head_dim=cfg.head_dim)
    w.add_meta("qwen3.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
        w.add_tensor_f32(b + "attn_q_norm.weight",
                         sd[p + "self_attn.q_norm.weight"])
        w.add_tensor_f32(b + "attn_k_norm.weight",
                         sd[p + "self_attn.k_norm.weight"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()
    _check(str(tmp_path / "qwen3.gguf"), model)


def test_mixtral_sparse_moe_routing(tmp_path):
    """mixtral: top-2 sparse MoE — router softmax-renormalisation over the
    selected experts, per-expert gated MLPs, and the llama q/k permute,
    all validated against transformers' MixtralForCausalLM."""
    cfg = transformers.MixtralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=128, rope_theta=10000.0,
        attn_implementation="eager")
    torch.manual_seed(7)
    model = transformers.MixtralForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "mixtral.gguf"))
    _base_meta(w, "llama", cfg)     # mixtral ships as arch "llama" in GGUF
    w.add_meta("llama.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_meta("llama.expert_count", cfg.num_local_experts)
    w.add_meta("llama.expert_used_count", cfg.num_experts_per_tok)
    H, KvH = cfg.num_attention_heads, cfg.num_key_value_heads
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        w.add_tensor_f32(b + "attn_q.weight",
                         hf_permute(sd[p + "self_attn.q_proj.weight"], H))
        w.add_tensor_f32(b + "attn_k.weight",
                         hf_permute(sd[p + "self_attn.k_proj.weight"], KvH))
        w.add_tensor_f32(b + "attn_v.weight",
                         sd[p + "self_attn.v_proj.weight"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        moe = p + "block_sparse_moe."
        w.add_tensor_f32(b + "ffn_gate_inp.weight", sd[moe + "gate.weight"])
        for e in range(cfg.num_local_experts):
            # HF w1 = gate, w3 = up, w2 = down (all [out, in])
            w.add_tensor_f32(b + f"ffn_gate.{e}.weight",
                             sd[moe + f"experts.{e}.w1.weight"])
            w.add_tensor_f32(b + f"ffn_up.{e}.weight",
                             sd[moe + f"experts.{e}.w3.weight"])
            w.add_tensor_f32(b + f"ffn_down.{e}.weight",
                             sd[moe + f"experts.{e}.w2.weight"])
    w.write()
    _check(str(tmp_path / "mixtral.gguf"), model)


# ---------------------------------------------------------------------------
# round-4 preset coverage: llama3.1/3.2-style scaled rope + tied embeddings,
# qwen2.5-style yarn (VERDICT r3 items 4 & 8). Positions run PAST the
# original context window so a wrong per-frequency rescale cannot hide.
# ---------------------------------------------------------------------------

IDS_LONG = (IDS * 5)[:48]     # 48 tokens > the 16/32-token original windows


def _llama3_freq_divisors(hf_cfg):
    """The rope_freqs.weight tensor a llama3.1-family GGUF conversion
    bakes: per-frequency divisors equal to base inv_freq / scaled."""
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS
    inv, _ = ROPE_INIT_FUNCTIONS["llama3"](hf_cfg, device=torch.device("cpu"))
    hd = getattr(hf_cfg, "head_dim", None) or (
        hf_cfg.hidden_size // hf_cfg.num_attention_heads)
    half = hd // 2
    base = 1.0 / hf_cfg.rope_theta ** (np.arange(half) / half)
    return (base / inv.numpy()).astype(np.float32)


def _export_llama(path, model, cfg, tied=False, extra_meta=(),
                  extra_tensors=()):
    sd = _sd(model)
    w = W.GGUFWriter(path)
    _base_meta(w, "llama", cfg)
    w.add_meta("llama.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    for k, v in extra_meta:
        w.add_meta(k, v)
    for name, arr in extra_tensors:
        w.add_tensor_f32(name, arr)
    H, KvH = cfg.num_attention_heads, cfg.num_key_value_heads
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    if not tied:
        w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        w.add_tensor_f32(b + "attn_q.weight",
                         hf_permute(sd[p + "self_attn.q_proj.weight"], H))
        w.add_tensor_f32(b + "attn_k.weight",
                         hf_permute(sd[p + "self_attn.k_proj.weight"], KvH))
        w.add_tensor_f32(b + "attn_v.weight",
                         sd[p + "self_attn.v_proj.weight"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()


def _check_long(path, model, rtol=3e-4, atol=3e-4):
    with torch.no_grad():
        ref = model(torch.tensor([IDS_LONG])).logits[0].numpy() \
            .astype(np.float64)
    cfg, params, _ = transcode_load(path, dtype=np.float32)
    params = jax.tree.map(jnp.asarray, params)
    logits, _, _ = decoder.prefill_chunk(
        params, cfg, jnp.asarray(np.array(IDS_LONG, np.int32)[None]))
    got = np.asarray(logits[0], np.float64)
    assert np.abs(ref).max() > 0.05
    np.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)


def test_llama31_rope_freqs_past_native_window(tmp_path):
    """llama3.1-style: the GGUF carries a pre-baked rope_freqs.weight
    divisor tensor; logits must match transformers' llama3-rope math at
    positions past the original context window."""
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        rope_scaling={"rope_type": "llama3", "factor": 4.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32},
        attn_implementation="eager")
    torch.manual_seed(8)
    model = transformers.LlamaForCausalLM(cfg).eval()
    path = str(tmp_path / "llama31.gguf")
    _export_llama(path, model, cfg, extra_tensors=[
        ("rope_freqs.weight", _llama3_freq_divisors(cfg))])
    mcfg, _, _ = transcode_load(path, dtype=np.float32)
    assert mcfg.rope_freq_factors is not None
    _check_long(path, model)


def test_llama32_style_tied_head_with_scaled_rope(tmp_path):
    """llama3.2-style: arch "llama" with NO output tensor (tied head —
    the arch-generic fallback, not a qwen special case) plus the
    rope_freqs divisors."""
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        tie_word_embeddings=True,
        rope_scaling={"rope_type": "llama3", "factor": 4.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 32},
        attn_implementation="eager")
    torch.manual_seed(9)
    model = transformers.LlamaForCausalLM(cfg).eval()
    path = str(tmp_path / "llama32.gguf")
    _export_llama(path, model, cfg, tied=True, extra_tensors=[
        ("rope_freqs.weight", _llama3_freq_divisors(cfg))])
    mcfg, _, _ = transcode_load(path, dtype=np.float32)
    assert mcfg.tie_embeddings
    _check_long(path, model)


def test_qwen25_yarn_past_native_window(tmp_path):
    """qwen2.5's 128k mode is qwen2 + YaRN: rope.scaling.{type,factor,
    original_context_length} metadata → NTK-by-parts rescale + the
    0.1·ln(s)+1 attention factor, vs transformers' yarn implementation."""
    cfg = transformers.Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 32},
        attn_implementation="eager")
    torch.manual_seed(10)
    model = transformers.Qwen2ForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "qwen25.gguf"))
    _base_meta(w, "qwen2", cfg)
    w.add_meta("qwen2.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_meta("qwen2.rope.scaling.type", "yarn")
    w.add_meta("qwen2.rope.scaling.factor", 4.0)
    w.add_meta("qwen2.rope.scaling.original_context_length", 32)
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
            w.add_tensor_f32(b + dst + ".bias",
                             sd[p + f"self_attn.{src}.bias"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()
    mcfg, _, _ = transcode_load(str(tmp_path / "qwen25.gguf"),
                                dtype=np.float32)
    assert mcfg.rope_scaling_type == "yarn"
    _check_long(str(tmp_path / "qwen25.gguf"), model)


def _write_phi3(path, cfg, sd, long_factor=None, short_factor=None,
                orig_ctx=None):
    """phi3 GGUF per the llama.cpp conversion: FUSED attn_qkv and
    gate+up ffn_up (HF keeps them fused too — qkv_proj / gate_up_proj),
    no rope permute (NEOX half-split layout), longrope as
    rope_factors_{long,short}.weight divisor tensors."""
    w = W.GGUFWriter(path)
    _base_meta(w, "phi3", cfg)
    w.add_meta("phi3.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    if orig_ctx:
        # real conversions declare the type too — the loader must accept
        # (not reject) the "longrope" string and route to the tensors
        w.add_meta("phi3.rope.scaling.type", "longrope")
        w.add_meta("phi3.rope.scaling.original_context_length", orig_ctx)
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    if long_factor is not None:
        w.add_tensor_f32("rope_factors_long.weight",
                         np.asarray(long_factor, np.float32))
        w.add_tensor_f32("rope_factors_short.weight",
                         np.asarray(short_factor, np.float32))
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        w.add_tensor_f32(b + "attn_qkv.weight",
                         sd[p + "self_attn.qkv_proj.weight"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_up.weight",
                         sd[p + "mlp.gate_up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()


def test_phi3_fused_qkv_gate_up(tmp_path):
    """phi3 fused qkv + gate_up source tensors, at GQA shapes (kv < q —
    phi3:14b/medium): the transcoder's UNEQUAL split offsets must
    reproduce transformers Phi3 logits (the longrope test covers the
    mini-style MHA split)."""
    cfg = transformers.Phi3Config(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        pad_token_id=0, attn_implementation="eager")
    torch.manual_seed(5)
    model = transformers.Phi3ForCausalLM(cfg).eval()
    path = str(tmp_path / "phi3.gguf")
    _write_phi3(path, cfg, _sd(model))
    _check(path, model)


def test_phi3_longrope_past_original_window(tmp_path):
    """phi3 longrope: the long-factor divisors + the magnitude factor
    sqrt(1 + ln(ctx/orig)/ln(orig)) must match transformers Phi3 on a
    sequence past the ORIGINAL window (transformers selects factors per
    forward length; llama.cpp — and we — select statically by the
    serving context, so parity holds exactly in the extended regime the
    128k tags serve)."""
    rng = np.random.default_rng(11)
    half = 8                                        # head_dim 16
    long_f = (1.0 + rng.random(half) * 3.0).astype(np.float32)
    short_f = np.ones(half, np.float32)
    cfg = transformers.Phi3Config(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=128,
        original_max_position_embeddings=8,
        rope_scaling={"type": "longrope",
                      "long_factor": [float(x) for x in long_f],
                      "short_factor": [float(x) for x in short_f]},
        rope_theta=10000.0, pad_token_id=0, attn_implementation="eager")
    torch.manual_seed(7)
    model = transformers.Phi3ForCausalLM(cfg).eval()
    path = str(tmp_path / "phi3lr.gguf")
    _write_phi3(path, cfg, _sd(model), long_factor=long_f,
                short_factor=short_f, orig_ctx=8)
    # IDS is 12 tokens > the 8-token original window: transformers picks
    # the long factors for the whole forward, matching the static choice
    _check(path, model)


def test_starcoder2_layernorm_bias_plain_mlp(tmp_path):
    """starcoder2 (3/7/15B): sequential pre-LN block with LayerNorm +
    biases everywhere, plain gelu-tanh MLP (c_fc/c_proj), tied
    embeddings, NEOX rotary — our transcode+forward must reproduce
    transformers Starcoder2 logits."""
    cfg = transformers.Starcoder2Config(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        sliding_window=None, attn_implementation="eager")
    torch.manual_seed(9)
    model = transformers.Starcoder2ForCausalLM(cfg).eval()
    sd = _sd(model)
    path = str(tmp_path / "sc2.gguf")
    w = W.GGUFWriter(path)
    _base_meta(w, "starcoder2", cfg)
    w.add_meta("starcoder2.attention.layer_norm_epsilon",
               float(cfg.norm_epsilon))
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output_norm.bias", sd["model.norm.bias"])
    # tied head: no output.weight tensor (llama.cpp falls back to embd)
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        w.add_tensor_f32(b + "attn_norm.bias",
                         sd[p + "input_layernorm.bias"])
        for t, hf in (("q", "q_proj"), ("k", "k_proj"), ("v", "v_proj")):
            w.add_tensor_f32(b + f"attn_{t}.weight",
                             sd[p + f"self_attn.{hf}.weight"])
            w.add_tensor_f32(b + f"attn_{t}.bias",
                             sd[p + f"self_attn.{hf}.bias"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "attn_output.bias",
                         sd[p + "self_attn.o_proj.bias"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_norm.bias",
                         sd[p + "post_attention_layernorm.bias"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.c_fc.weight"])
        w.add_tensor_f32(b + "ffn_up.bias", sd[p + "mlp.c_fc.bias"])
        w.add_tensor_f32(b + "ffn_down.weight", sd[p + "mlp.c_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.bias", sd[p + "mlp.c_proj.bias"])
    w.write()
    _check(path, model)


def test_qwen3moe_sparse_moe_qk_norm(tmp_path):
    """qwen3moe (qwen3:30b-a3b class): qwen3's per-head q/k RMS norms
    composed with sparse MoE MLPs — router softmax renormalised over the
    selected top-k (norm_topk_prob), merged expert tensors, NEOX layout —
    against transformers Qwen3MoeForCausalLM."""
    cfg = transformers.Qwen3MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=48, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=128, rope_theta=10000.0,
        attn_implementation="eager")
    torch.manual_seed(21)
    model = transformers.Qwen3MoeForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "q3moe.gguf"))
    _base_meta(w, "qwen3moe", cfg, head_dim=cfg.head_dim)
    w.add_meta("qwen3moe.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_meta("qwen3moe.expert_count", cfg.num_experts)
    w.add_meta("qwen3moe.expert_used_count", cfg.num_experts_per_tok)
    w.add_meta("qwen3moe.expert_feed_forward_length",
               cfg.moe_intermediate_size)
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    E = cfg.num_experts
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v"), ("o_proj", "attn_output")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
        w.add_tensor_f32(b + "attn_q_norm.weight",
                         sd[p + "self_attn.q_norm.weight"])
        w.add_tensor_f32(b + "attn_k_norm.weight",
                         sd[p + "self_attn.k_norm.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate_inp.weight",
                         sd[p + "mlp.gate.weight"])
        # merged expert tensors [E, F, D] (modern conversion layout)
        for kind, hf in (("gate", "gate_proj"), ("up", "up_proj"),
                         ("down", "down_proj")):
            stacked = np.stack([sd[p + f"mlp.experts.{e}.{hf}.weight"]
                                for e in range(E)])
            w.add_tensor_f32(b + f"ffn_{kind}_exps.weight", stacked)
    w.write()
    _check(str(tmp_path / "q3moe.gguf"), model)


def test_gemma3_dual_rope_pattern6(tmp_path):
    """gemma3: pattern-6 alternation (every 6th layer full attention),
    DUAL rope (sliding layers at the local 10k theta, full layers at the
    global theta with linear scaling), gemma-offset q/k RMS norms,
    sandwich norms, no softcapping — against transformers
    Gemma3ForCausalLM. 7 layers cover both layer types; linear rope
    scaling on the global rope exercises the split."""
    cfg = transformers.Gemma3TextConfig(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=7, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, sliding_window=8, rope_theta=1000000.0,
        rope_local_base_freq=10000.0, query_pre_attn_scalar=16,
        rope_scaling={"rope_type": "linear", "factor": 8.0},
        max_position_embeddings=256, pad_token_id=0,
        attn_implementation="eager")
    torch.manual_seed(23)
    model = transformers.Gemma3ForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "g3.gguf"))
    _base_meta(w, "gemma3", cfg, head_dim=cfg.head_dim)
    w.add_meta("gemma3.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_meta("gemma3.attention.sliding_window", cfg.sliding_window)
    w.add_meta("gemma3.attention.query_pre_attn_scalar",
               float(cfg.query_pre_attn_scalar))
    w.add_meta("gemma3.rope.scaling.type", "linear")
    w.add_meta("gemma3.rope.scaling.factor", 8.0)
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    # tied head: no output.weight tensor
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v"), ("o_proj", "attn_output")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
        w.add_tensor_f32(b + "attn_q_norm.weight",
                         sd[p + "self_attn.q_norm.weight"])
        w.add_tensor_f32(b + "attn_k_norm.weight",
                         sd[p + "self_attn.k_norm.weight"])
        w.add_tensor_f32(b + "attn_post_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "pre_feedforward_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_post_norm.weight",
                         sd[p + "post_feedforward_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()
    # 12 tokens exceed the 8-token sliding window, so sliding layers'
    # masks and the local rope both bind
    _check(str(tmp_path / "g3.gguf"), model)


def test_granite_scalar_multipliers(tmp_path):
    """granite3 dense: llama block + the four scalar multipliers
    (embedding/attention/residual/logits) and llama-permuted q/k —
    against transformers GraniteForCausalLM with non-trivial multiplier
    values so each hook must bind."""
    cfg = transformers.GraniteConfig(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0, pad_token_id=0,
        embedding_multiplier=6.0, attention_multiplier=0.0625,
        residual_multiplier=0.5, logits_scaling=4.0,
        attn_implementation="eager")
    torch.manual_seed(29)
    model = transformers.GraniteForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "granite.gguf"))
    _base_meta(w, "granite", cfg)
    w.add_meta("granite.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_meta("granite.attention.scale", float(cfg.attention_multiplier))
    w.add_meta("granite.embedding.scale", float(cfg.embedding_multiplier))
    w.add_meta("granite.residual.scale", float(cfg.residual_multiplier))
    w.add_meta("granite.logit_scale", float(cfg.logits_scaling))
    H, KvH = cfg.num_attention_heads, cfg.num_key_value_heads
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        w.add_tensor_f32(b + "attn_q.weight",
                         hf_permute(sd[p + "self_attn.q_proj.weight"], H))
        w.add_tensor_f32(b + "attn_k.weight",
                         hf_permute(sd[p + "self_attn.k_proj.weight"], KvH))
        w.add_tensor_f32(b + "attn_v.weight",
                         sd[p + "self_attn.v_proj.weight"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()
    _check(str(tmp_path / "granite.gguf"), model)


def test_command_r_parallel_biasfree_interleaved(tmp_path):
    """command-r (cohere): parallel attn+mlp block sharing one BIAS-FREE
    LayerNorm, gated MLP, tied embeddings, logits MULTIPLIED by
    logit_scale, and interleaved rope over unpermuted weights (rows
    re-ordered to half-split at load) — against transformers
    CohereForCausalLM."""
    cfg = transformers.CohereConfig(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0, pad_token_id=0,
        logit_scale=0.0625, attn_implementation="eager")
    torch.manual_seed(31)
    model = transformers.CohereForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "cmdr.gguf"))
    _base_meta(w, "command-r", cfg)
    w.add_meta("command-r.attention.layer_norm_epsilon",
               float(cfg.layer_norm_eps))
    w.add_meta("command-r.logit_scale", float(cfg.logit_scale))
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    # tied head: no output.weight
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v"), ("o_proj", "attn_output")):
            # UNPERMUTED — the loader's interleave->half transform runs
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
        w.add_tensor_f32(b + "ffn_gate.weight",
                         sd[p + "mlp.gate_proj.weight"])
        w.add_tensor_f32(b + "ffn_up.weight", sd[p + "mlp.up_proj.weight"])
        w.add_tensor_f32(b + "ffn_down.weight",
                         sd[p + "mlp.down_proj.weight"])
    w.write()
    _check(str(tmp_path / "cmdr.gguf"), model)


def test_qwen2moe_shared_expert_unrenormalised_gates(tmp_path):
    """qwen2moe (qwen1.5-moe / qwen2-57b-a14b class): qkv-bias attention
    + sparse MoE with UN-renormalised top-k gates (norm_topk_prob=false)
    and a sigmoid-gated SHARED expert every token runs — against
    transformers Qwen2MoeForCausalLM."""
    cfg = transformers.Qwen2MoeConfig(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        moe_intermediate_size=48, shared_expert_intermediate_size=80,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=False,
        decoder_sparse_step=1, mlp_only_layers=[],
        max_position_embeddings=128, rope_theta=10000.0, pad_token_id=0,
        attn_implementation="eager")
    torch.manual_seed(37)
    model = transformers.Qwen2MoeForCausalLM(cfg).eval()
    sd = _sd(model)
    w = W.GGUFWriter(str(tmp_path / "q2moe.gguf"))
    _base_meta(w, "qwen2moe", cfg)
    w.add_meta("qwen2moe.attention.layer_norm_rms_epsilon",
               float(cfg.rms_norm_eps))
    w.add_meta("qwen2moe.expert_count", cfg.num_experts)
    w.add_meta("qwen2moe.expert_used_count", cfg.num_experts_per_tok)
    w.add_meta("qwen2moe.expert_feed_forward_length",
               cfg.moe_intermediate_size)
    w.add_meta("qwen2moe.expert_shared_feed_forward_length",
               cfg.shared_expert_intermediate_size)
    w.add_tensor_f32("token_embd.weight", sd["model.embed_tokens.weight"])
    w.add_tensor_f32("output_norm.weight", sd["model.norm.weight"])
    w.add_tensor_f32("output.weight", sd["lm_head.weight"])
    E = cfg.num_experts
    for i in range(cfg.num_hidden_layers):
        p, b = f"model.layers.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_norm.weight",
                         sd[p + "input_layernorm.weight"])
        for src, dst in (("q_proj", "attn_q"), ("k_proj", "attn_k"),
                         ("v_proj", "attn_v")):
            w.add_tensor_f32(b + dst + ".weight",
                             sd[p + f"self_attn.{src}.weight"])
            w.add_tensor_f32(b + dst + ".bias",
                             sd[p + f"self_attn.{src}.bias"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "self_attn.o_proj.weight"])
        w.add_tensor_f32(b + "ffn_norm.weight",
                         sd[p + "post_attention_layernorm.weight"])
        w.add_tensor_f32(b + "ffn_gate_inp.weight",
                         sd[p + "mlp.gate.weight"])
        for kind, hf in (("gate", "gate_proj"), ("up", "up_proj"),
                         ("down", "down_proj")):
            stacked = np.stack([sd[p + f"mlp.experts.{e}.{hf}.weight"]
                                for e in range(E)])
            w.add_tensor_f32(b + f"ffn_{kind}_exps.weight", stacked)
            w.add_tensor_f32(b + f"ffn_{kind}_shexp.weight",
                             sd[p + f"mlp.shared_expert.{hf}.weight"])
        w.add_tensor_f32(b + "ffn_gate_inp_shexp.weight",
                         sd[p + "mlp.shared_expert_gate.weight"])
    w.write()
    _check(str(tmp_path / "q2moe.gguf"), model)


def test_phi4_shape_through_phi3_arch(tmp_path):
    """phi4 converts with GGUF arch "phi3" (same fused-tensor layout, no
    longrope, 16k context so no sliding-window default): the phi3 path
    must serve it unchanged — parity against transformers Phi3 at
    phi4-style settings (full attention, plain rope)."""
    cfg = transformers.Phi3Config(
        vocab_size=256, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=16384, rope_theta=250000.0,
        pad_token_id=0, attn_implementation="eager")
    torch.manual_seed(41)
    model = transformers.Phi3ForCausalLM(cfg).eval()
    path = str(tmp_path / "phi4.gguf")
    _write_phi3(path, cfg, _sd(model))
    from ollama_operator_tpu.gguf.reader import GGUFFile
    from ollama_operator_tpu.gguf.transcode import config_from_gguf
    with GGUFFile(path) as f:
        mcfg = config_from_gguf(f)
    # 16k context: the 4k-era sliding-window default must NOT apply
    assert mcfg.sliding_window == 0
    _check(path, model)
