"""Pipeline parallelism: the GPipe-schedule forwards must agree exactly
with the dense single-device decoder, across pp widths, microbatch counts,
combined pp×tp meshes, and MoE blocks (pp×ep)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.parallel import MeshPlan, make_mesh, set_mesh_compat
from ollama_operator_tpu.parallel import pipeline as PL
from ollama_operator_tpu.parallel.sharding import shard_params

F32 = jnp.float32


def tiny(name="tiny", **kw):
    base = cfglib.PRESETS[name]
    return cfglib.ModelConfig(**{**base.__dict__, **kw}).validate()


def make_cache(cfg, B, S, dtype=F32):
    shape = (cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def ref_state(cfg, params, tokens, split, S):
    """Dense prefill of tokens[:, :split] into an S-slot cache."""
    logits, ks, vs = decoder.prefill_chunk(params, cfg, tokens[:, :split])
    k_cache, v_cache = make_cache(cfg, tokens.shape[0], S)
    k_cache = k_cache.at[:, :, :, :split].set(ks)
    v_cache = v_cache.at[:, :, :, :split].set(vs)
    return logits, k_cache, v_cache


@pytest.mark.parametrize("pp,mb", [(2, 2), (4, 4), (2, 4)])
def test_pp_prefill_matches_dense(pp, mb):
    cfg = tiny(n_layers=4)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    B, T = 4, 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                cfg.vocab_size)
    ref, ref_k, ref_v = decoder.prefill_chunk(params, cfg, tokens)

    mesh = make_mesh(MeshPlan(pp=pp))
    logits, ks, vs = PL.prefill_chunk_pp(params, cfg, tokens, mesh,
                                         n_microbatches=mb)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(ks), np.asarray(ref_k),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(vs), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)


def test_pp_decode_matches_dense():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    B, T, split, S = 4, 12, 8, 32
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                cfg.vocab_size)
    ref_logits, _, _ = decoder.prefill_chunk(params, cfg, tokens)
    _, k_cache, v_cache = ref_state(cfg, params, tokens, split, S)
    lengths = jnp.full((B,), split, jnp.int32)

    mesh = make_mesh(MeshPlan(pp=2))
    for i in range(split, T):
        logits, k_cache, v_cache = PL.forward_with_cache_pp(
            params, cfg, tokens[:, i:i + 1], k_cache, v_cache, lengths, mesh)
        lengths = lengths + 1
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(ref_logits[:, i]),
                                   rtol=3e-4, atol=3e-4)


def test_pp_tp_mesh_matches_dense():
    """pp manual + tp GSPMD-auto in the same program (Megatron sharding on
    each stage's weights stays live inside the manual region)."""
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    B, T = 4, 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                cfg.vocab_size)
    ref, _, _ = decoder.prefill_chunk(params, cfg, tokens)

    mesh = make_mesh(MeshPlan(pp=2, tp=4))
    with set_mesh_compat(mesh):
        sharded = shard_params(params, mesh, cfg)
        fn = jax.jit(lambda p, t: PL.prefill_chunk_pp(p, cfg, t, mesh))
        logits, _, _ = fn(sharded, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_pp_moe_ep_mesh_matches_dense():
    """MoE blocks inside pipeline stages, experts ep-sharded: pp manual ×
    ep/tp auto — the full 5-axis story in one program."""
    cfg = tiny("tiny-moe", moe_impl="einsum")
    params = decoder.init_params(cfg, jax.random.PRNGKey(2), dtype=F32)
    B, T = 4, 8
    tokens = jax.random.randint(jax.random.PRNGKey(3), (B, T), 0,
                                cfg.vocab_size)
    ref, _, _ = decoder.prefill_chunk(params, cfg, tokens)

    mesh = make_mesh(MeshPlan(pp=2, ep=2, tp=2))
    with set_mesh_compat(mesh):
        sharded = shard_params(params, mesh, cfg)
        fn = jax.jit(lambda p, t: PL.prefill_chunk_pp(p, cfg, t, mesh))
        logits, _, _ = fn(sharded, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_split_merge_stages_roundtrip():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    st = PL.split_stages(params["layers"], 2)
    back = PL.merge_stages(st)
    for k in params["layers"]:
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(params["layers"][k]))
