"""Prefix-cache continuation (engine.extend + scheduler parking): reusing a
parked slot's KV for a shared prompt prefix must be bit-identical to a fresh
full prefill — including the repeat-penalty window, which is rebuilt for the
continuation sequence."""

import jax
import jax.numpy as jnp
import numpy as np

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.scheduler import Scheduler

F32 = jnp.float32
GREEDY = SlotOptions(temperature=0.0, repeat_penalty=1.0)
GREEDY_PEN = SlotOptions(temperature=0.0, repeat_penalty=1.3,
                         presence_penalty=0.2)


def make_engine(cfg, params, slots=4):
    return Engine(cfg, params,
                  ecfg=EngineConfig(max_slots=slots, max_seq_len=128,
                                    cache_dtype=F32, min_prefill_bucket=16,
                                    repeat_last_n=8))


def run_fresh(eng, prompt, opts, n_steps):
    slot = eng.free_slots()[0]
    got = [eng.admit(slot, np.asarray(prompt, np.int32), opts)]
    for _ in range(n_steps):
        got.append(int(eng.decode()[slot]))
    eng.release(slot)
    return got


def test_extend_matches_fresh_prefill():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params)

    p1 = list(np.random.default_rng(0).integers(1, 250, 24))
    first = eng.admit(0, np.asarray(p1, np.int32), GREEDY)
    gen = [first] + [int(eng.decode()[0]) for _ in range(4)]
    eng.release(0, park=True)
    parked_ids = p1 + gen

    # continuation: full conversation + a new turn. The cached prefix
    # excludes gen's LAST token (sampled, never fed — its K/V was never
    # written; the scheduler's parked map applies the same -1), so the
    # tail re-feeds it.
    new_prompt = parked_ids + [7, 13, 52]
    got = [eng.extend(0, np.asarray(new_prompt, np.int32),
                      start=len(parked_ids) - 1, opts=GREEDY)]
    for _ in range(5):
        got.append(int(eng.decode()[0]))
    eng.release(0)

    ref = run_fresh(make_engine(cfg, params), new_prompt, GREEDY, 5)
    assert got == ref


def test_extend_partial_divergent_prefix():
    """Reuse only the common prefix of a parked conversation that then
    diverged — stale cache entries beyond the prefix must not leak."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params)

    shared = list(np.random.default_rng(1).integers(1, 250, 20))
    p1 = shared + [3, 5, 7]
    eng.admit(1, np.asarray(p1, np.int32), GREEDY)
    for _ in range(3):
        eng.decode()
    eng.release(1, park=True)

    new_prompt = shared + [9, 11]  # diverges after the shared prefix
    got = [eng.extend(1, np.asarray(new_prompt, np.int32),
                      start=len(shared), opts=GREEDY)]
    for _ in range(4):
        got.append(int(eng.decode()[1]))
    eng.release(1)

    ref = run_fresh(make_engine(cfg, params), new_prompt, GREEDY, 4)
    assert got == ref


def test_extend_rebuilds_penalty_window():
    """With repeat/presence penalties on, the extension's ring must cover
    the continuation prompt, not the parked sequence's divergent tail."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params)

    shared = list(np.random.default_rng(2).integers(1, 250, 18))
    eng.admit(0, np.asarray(shared + [101, 102, 103], np.int32), GREEDY_PEN)
    for _ in range(3):
        eng.decode()
    eng.release(0, park=True)

    new_prompt = shared + [44, 45, 46, 47]
    got = [eng.extend(0, np.asarray(new_prompt, np.int32),
                      start=len(shared), opts=GREEDY_PEN)]
    for _ in range(6):
        got.append(int(eng.decode()[0]))
    eng.release(0)

    ref = run_fresh(make_engine(cfg, params), new_prompt, GREEDY_PEN, 6)
    assert got == ref


def test_scheduler_parks_and_reuses():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params, slots=2)
    sched = Scheduler(eng)
    try:
        p1 = list(np.random.default_rng(3).integers(1, 250, 20))
        r1 = sched.submit(p1, GREEDY, max_tokens=4)
        out1 = list(r1.tokens())
        assert r1.stats.n_reused == 0

        # conversation continuation: old prompt + old output + new turn
        p2 = p1 + out1 + [17, 23]
        r2 = sched.submit(p2, GREEDY, max_tokens=4)
        out2 = list(r2.tokens())
        assert r2.stats.n_reused >= len(p1)

        # a fresh scheduler with no cache must produce the same stream
        eng_ref = make_engine(cfg, params, slots=2)
        sched_ref = Scheduler(eng_ref)
        try:
            rr = sched_ref.submit(p2, GREEDY, max_tokens=4)
            assert list(rr.tokens()) == out2
        finally:
            sched_ref.shutdown()
    finally:
        sched.shutdown()


def test_scheduler_short_prompts_skip_reuse():
    """Prefixes below MIN_PREFIX_REUSE go through the normal admit path."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params, slots=2)
    sched = Scheduler(eng)
    try:
        r1 = sched.submit([5, 9, 2], GREEDY, max_tokens=3)
        list(r1.tokens())
        r2 = sched.submit([5, 9, 2, 4], GREEDY, max_tokens=3)
        list(r2.tokens())
        assert r2.stats.n_reused == 0
    finally:
        sched.shutdown()


def test_parked_prefix_excludes_unfed_last_token():
    """With decode_chunk=1 every sampled token sits on the final chunk row,
    so the last token's K/V is never written; parking must exclude it or
    continuations would attend a stale cache position."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = Engine(cfg, params,
                 ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                   cache_dtype=F32, min_prefill_bucket=16,
                                   repeat_last_n=8, decode_chunk=1))
    sched = Scheduler(eng)
    try:
        p1 = list(np.random.default_rng(5).integers(1, 250, 20))
        r1 = sched.submit(p1, GREEDY, max_tokens=4)
        out1 = list(r1.tokens())
        parked = sched._parked.get(r1.slot)
        assert parked is not None
        # every sampled token (incl. a hypothetical EOG) minus the unfed last
        assert len(parked) == len(p1) + len(r1.all_tokens) - 1

        p2 = p1 + out1 + [17, 23]
        r2 = sched.submit(p2, GREEDY, max_tokens=4)
        out2 = list(r2.tokens())
        assert r2.stats.n_reused >= len(p1)

        eng_ref = Engine(cfg, params,
                         ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                           cache_dtype=F32,
                                           min_prefill_bucket=16,
                                           repeat_last_n=8, decode_chunk=1))
        sched_ref = Scheduler(eng_ref)
        try:
            rr = sched_ref.submit(p2, GREEDY, max_tokens=4)
            assert list(rr.tokens()) == out2
        finally:
            sched_ref.shutdown()
    finally:
        sched.shutdown()


def test_extend_int8_dense_cache():
    """int8 KV × prefix cache on the DENSE cache (round-1 weak #4: these
    were mutually exclusive; extend now slices entries + scales and the
    cached forward quantizes the tail in place). Parity is against a
    fresh int8 prefill — quantization noise is identical on both sides
    because the prefix entries are bit-identical."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)

    def make_q(slots=4):
        return Engine(cfg, params,
                      ecfg=EngineConfig(max_slots=slots, max_seq_len=128,
                                        cache_dtype=jnp.int8,
                                        min_prefill_bucket=16,
                                        repeat_last_n=8))

    eng = make_q()
    assert eng.supports_extend
    p1 = list(np.random.default_rng(1).integers(1, 250, 24))
    first = eng.admit(0, np.asarray(p1, np.int32), GREEDY)
    gen = [first] + [int(eng.decode()[0]) for _ in range(4)]
    eng.release(0, park=True)
    parked_ids = p1 + gen
    new_prompt = parked_ids + [7, 13, 52]
    got = [eng.extend(0, np.asarray(new_prompt, np.int32),
                      start=len(parked_ids) - 1, opts=GREEDY)]
    for _ in range(5):
        got.append(int(eng.decode()[0]))

    ref = run_fresh(make_q(), new_prompt, GREEDY, 5)
    assert got == ref


def test_extend_sp_sequence_sharded_cache():
    """sp caches extend too (round-2 weak #5): an sp=2 engine's
    continuation must match its own fresh full prefill token-for-token,
    and the single-device dense engine's output as well."""
    from ollama_operator_tpu.parallel import MeshPlan, make_mesh
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)

    def sp_engine():
        mesh = make_mesh(MeshPlan(sp=2))
        return Engine(cfg, params, mesh=mesh,
                      ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                        cache_dtype=F32,
                                        min_prefill_bucket=16,
                                        repeat_last_n=8))

    eng = sp_engine()
    assert eng.supports_extend
    p1 = list(np.random.default_rng(3).integers(1, 250, 24))
    first = eng.admit(0, np.asarray(p1, np.int32), GREEDY)
    gen = [first] + [int(eng.decode()[0]) for _ in range(4)]
    eng.release(0, park=True)
    parked_ids = p1 + gen

    new_prompt = parked_ids + [7, 13, 52]
    # the cached prefix excludes gen's LAST token (sampled, never fed —
    # its K/V was never written; the scheduler's parked map applies the
    # same -1), so the tail re-feeds it
    got = [eng.extend(0, np.asarray(new_prompt, np.int32),
                      start=len(parked_ids) - 1, opts=GREEDY)]
    for _ in range(5):
        got.append(int(eng.decode()[0]))
    eng.release(0)

    ref_sp = run_fresh(sp_engine(), new_prompt, GREEDY, 5)
    assert got == ref_sp
    ref_dense = run_fresh(make_engine(cfg, params, slots=2), new_prompt,
                          GREEDY, 5)
    assert got == ref_dense


def test_extend_sp_int8_cache():
    """sp extend with the quantized sequence-sharded cache: the tail
    quantizes in place per shard; greedy continuation matches the sp
    engine's own fresh prefill."""
    from ollama_operator_tpu.parallel import MeshPlan, make_mesh
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)

    def sp_engine():
        mesh = make_mesh(MeshPlan(sp=2))
        return Engine(cfg, params, mesh=mesh,
                      ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                        cache_dtype=jnp.int8,
                                        min_prefill_bucket=16,
                                        repeat_last_n=8))

    eng = sp_engine()
    assert eng.supports_extend
    p1 = list(np.random.default_rng(4).integers(1, 250, 20))
    first = eng.admit(0, np.asarray(p1, np.int32), GREEDY)
    gen = [first] + [int(eng.decode()[0]) for _ in range(3)]
    eng.release(0, park=True)
    parked_ids = p1 + gen

    new_prompt = parked_ids + [9, 41]
    got = [eng.extend(0, np.asarray(new_prompt, np.int32),
                      start=len(parked_ids) - 1, opts=GREEDY)]
    for _ in range(4):
        got.append(int(eng.decode()[0]))
    eng.release(0)

    ref = run_fresh(sp_engine(), new_prompt, GREEDY, 4)
    assert got == ref


def test_extend_paged_dp_sharded_pool():
    """paged×dp extend (the matrix's last hole): the tail replicates
    across dp shards with owner-real/others-trash table rows; greedy
    continuation matches the same engine's fresh full prefill and the
    single-device dense engine."""
    from ollama_operator_tpu.parallel import MeshPlan, make_mesh
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)

    def dp_engine():
        mesh = make_mesh(MeshPlan(dp=2))
        return Engine(cfg, params, mesh=mesh,
                      ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                        cache_dtype=F32,
                                        min_prefill_bucket=16,
                                        repeat_last_n=8, paged=True,
                                        page_size=16))

    eng = dp_engine()
    assert eng.supports_extend
    p1 = list(np.random.default_rng(7).integers(1, 250, 24))
    first = eng.admit(0, np.asarray(p1, np.int32), GREEDY)
    gen = [first] + [int(eng.decode()[0]) for _ in range(4)]
    eng.release(0, park=True)
    parked_ids = p1 + gen

    new_prompt = parked_ids + [7, 13, 52]
    got = [eng.extend(0, np.asarray(new_prompt, np.int32),
                      start=len(parked_ids) - 1, opts=GREEDY)]
    for _ in range(5):
        got.append(int(eng.decode()[0]))
    eng.release(0)

    ref_dp = run_fresh(dp_engine(), new_prompt, GREEDY, 5)
    assert got == ref_dp
    ref_dense = run_fresh(make_engine(cfg, params, slots=2), new_prompt,
                          GREEDY, 5)
    assert got == ref_dense


def test_extend_paged_dp_slot_on_second_shard():
    """Same as above but the slot lives on dp shard 1 — the owner-select
    psum must pick the non-zero shard's logits."""
    from ollama_operator_tpu.parallel import MeshPlan, make_mesh
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    mesh = make_mesh(MeshPlan(dp=2))
    eng = Engine(cfg, params, mesh=mesh,
                 ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                   cache_dtype=jnp.int8,
                                   min_prefill_bucket=16,
                                   repeat_last_n=8, paged=True,
                                   page_size=16))
    slot = 1                      # slots_per_shard = 1 → shard_of(1) == 1
    assert eng._pt.shard_of(slot) == 1
    p1 = list(np.random.default_rng(8).integers(1, 250, 20))
    first = eng.admit(slot, np.asarray(p1, np.int32), GREEDY)
    gen = [first] + [int(eng.decode()[slot]) for _ in range(3)]
    eng.release(slot, park=True)
    parked_ids = p1 + gen

    new_prompt = parked_ids + [9, 41]
    got = [eng.extend(slot, np.asarray(new_prompt, np.int32),
                      start=len(parked_ids) - 1, opts=GREEDY)]
    for _ in range(4):
        got.append(int(eng.decode()[slot]))

    ref = run_fresh(make_engine(cfg, params, slots=2), new_prompt,
                    GREEDY, 4)
    assert got == ref
