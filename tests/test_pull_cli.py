"""Puller init-container client (server/pull.py): the retry taxonomy.

The init container must retry while the store is coming up (connection
refused, 5xx) but exit non-zero immediately on a definitive 4xx so bad
model references surface in pod status instead of spinning for 90 min.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ollama_operator_tpu.server.pull import pull, resolve_host


def _serve(handler_cls):
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


class TestResolveHost:
    def test_forms(self):
        assert resolve_host("store.ns") == "http://store.ns:11434"
        assert resolve_host("store:80") == "http://store:80"
        assert resolve_host("http://x:1234/") == "http://x:1234"
        assert resolve_host("") == "http://127.0.0.1:11434"


class TestPull:
    def test_404_fails_fast_without_retry(self):
        calls = []

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                calls.append(1)
                body = b'{"error":"model not found"}'
                self.send_response(404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = _serve(H)
        try:
            rc = pull("nope", f"127.0.0.1:{httpd.server_address[1]}",
                      retries=50, retry_delay=0.01)
            assert rc == 1
            assert len(calls) == 1  # no retries on 4xx
        finally:
            httpd.shutdown()

    def test_5xx_retries_then_succeeds(self):
        calls = []

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                calls.append(1)
                if len(calls) < 3:
                    self.send_response(503)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = json.dumps({"status": "success"}).encode() + b"\n"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = _serve(H)
        try:
            rc = pull("m", f"127.0.0.1:{httpd.server_address[1]}",
                      retries=10, retry_delay=0.01)
            assert rc == 0 and len(calls) == 3
        finally:
            httpd.shutdown()

    def test_connection_refused_retries_until_cap(self):
        rc = pull("m", "127.0.0.1:1", retries=3, retry_delay=0.01)
        assert rc == 1

    def test_error_event_in_stream_fails(self):
        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                body = b'{"error": "blob digest mismatch"}\n'
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = _serve(H)
        try:
            assert pull("m", f"127.0.0.1:{httpd.server_address[1]}",
                        retries=1) == 1
        finally:
            httpd.shutdown()
