"""Weight-only int8 quantization: groupwise quantize/dequant round-trip,
XLA grouped matmul vs reference, pallas fused kernel (interpret) parity,
quantized decoder forward accuracy, TP-sharded quantized params, and the
engine running fully quantized end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.ops import quant as Q
from ollama_operator_tpu.ops.pallas.quant import qmm_pallas
from ollama_operator_tpu.parallel import (MeshPlan, make_mesh,
                                           set_mesh_compat, shard_params)
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

rng = np.random.default_rng(5)


def tiny(**kw):
    base = cfglib.PRESETS["tiny"]
    return cfglib.ModelConfig(**{**base.__dict__, **kw}).validate()


def test_quantize_dequantize_roundtrip():
    w = rng.standard_normal((64, 48)).astype(np.float32)
    qw = Q.quantize_groupwise(w, group=32)
    assert qw["q"].dtype == np.int8
    assert qw["q"].shape == (64, 48)
    assert qw["s"].shape == (2, 48)
    back = np.asarray(Q.dequantize_groupwise(qw))
    # int8 groupwise: max error is half a step = amax/254 per group
    err = np.abs(back - w)
    step = np.abs(w).reshape(2, 32, 48).max(1, keepdims=True) / 127.0
    assert (err.reshape(2, 32, 48) <= 0.51 * step + 1e-7).all()


def test_quantize_already_int8_grid_is_lossless():
    """Weights that already sit on a symmetric int8 g=32 grid (i.e. what a
    GGUF q8_0 tensor dequantizes to) must survive requantization exactly."""
    q = rng.integers(-126, 127, (64, 16)).astype(np.int8)
    # q8_0 scale is amax/127, so every group's max quant hits ±127
    q.reshape(2, 32, 16)[:, 0, :] = 127
    s = (rng.random((2, 16)).astype(np.float32) + 0.5) / 127.0
    w = np.asarray(Q.dequantize_groupwise({"q": q, "s": s}))
    qw = Q.quantize_groupwise(w, group=32)
    back = np.asarray(Q.dequantize_groupwise(qw))
    np.testing.assert_allclose(back, w, rtol=1e-6, atol=1e-7)


def test_qmm_matches_dequant_matmul():
    x = jnp.asarray(rng.standard_normal((3, 5, 64)), jnp.float32)
    w = rng.standard_normal((64, 48)).astype(np.float32)
    qw = jax.tree_util.tree_map(jnp.asarray, Q.quantize_groupwise(w, 32))
    want = np.asarray(x) @ np.asarray(Q.dequantize_groupwise(qw))
    got = Q.qmm(x, qw)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("B,K,O", [(1, 64, 128), (8, 256, 256), (5, 128, 384)])
def test_qmm_pallas_interpret_matches_xla(B, K, O):
    x = jnp.asarray(rng.standard_normal((B, K)), jnp.float32)
    w = rng.standard_normal((K, O)).astype(np.float32)
    qw = jax.tree_util.tree_map(jnp.asarray, Q.quantize_groupwise(w, 32))
    ref = Q.qmm(x, qw, out_dtype=jnp.float32)
    got = qmm_pallas(x, qw["q"], qw["s"], interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_qmm_pallas_fallback_odd_shapes():
    """Shapes that don't tile must silently use the XLA path."""
    x = jnp.asarray(rng.standard_normal((2, 48)), jnp.float32)
    w = rng.standard_normal((48, 40)).astype(np.float32)
    qw = jax.tree_util.tree_map(jnp.asarray, Q.quantize_groupwise(w, 16))
    ref = Q.qmm(x, qw, out_dtype=jnp.float32)
    got = qmm_pallas(x, qw["q"], qw["s"], interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_quantized_decoder_close_to_dense():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = Q.quantize_params(
        jax.tree_util.tree_map(np.asarray, params))
    qparams = jax.tree_util.tree_map(jnp.asarray, qparams)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0,
                                cfg.vocab_size)
    ref, _, _ = decoder.prefill_chunk(params, cfg, tokens)
    got, _, _ = decoder.prefill_chunk(qparams, cfg, tokens)
    # weight-only int8: logits drift slightly but ranking must agree
    ref_n, got_n = np.asarray(ref), np.asarray(got)
    assert np.abs(ref_n - got_n).max() < 0.15 * np.abs(ref_n).max() + 0.05
    agree = (ref_n.argmax(-1) == got_n.argmax(-1)).mean()
    assert agree > 0.9


def test_quantized_params_tp_sharded_matches_single_device():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = jax.tree_util.tree_map(
        jnp.asarray, Q.quantize_params(jax.tree_util.tree_map(
            np.asarray, params)))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    ref, _, _ = decoder.prefill_chunk(qparams, cfg, tokens)

    mesh = make_mesh(MeshPlan(tp=4))
    with set_mesh_compat(mesh):
        sharded = shard_params(qparams, mesh, cfg)
        fn = jax.jit(lambda p, t: decoder.prefill_chunk(p, cfg, t))
        out, _, _ = fn(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_engine_int8_params_decode():
    """Engine end-to-end with quantized weights: greedy tokens match the
    dequantized-dense engine (same numeric path, g=32 exact grid)."""
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    qparams_np = Q.quantize_params(jax.tree_util.tree_map(np.asarray, params))
    dq = {}
    for k, v in qparams_np.items():
        if k == "layers":
            dq[k] = {lk: (Q.dequantize_groupwise(lv) if Q.is_quantized(lv)
                          else jnp.asarray(lv)) for lk, lv in v.items()}
        else:
            dq[k] = (Q.dequantize_groupwise(v) if Q.is_quantized(v)
                     else jnp.asarray(v))
    qparams = jax.tree_util.tree_map(jnp.asarray, qparams_np)

    ecfg = EngineConfig(max_slots=2, max_seq_len=64, min_prefill_bucket=8,
                        cache_dtype=jnp.float32)
    opts = SlotOptions(temperature=0.0)
    prompt = np.asarray(rng.integers(1, cfg.vocab_size, 11), np.int32)

    eng_q = Engine(cfg, qparams, ecfg=ecfg)
    tq = [eng_q.admit(0, prompt, opts)]
    for _ in range(5):
        tq.append(int(eng_q.decode()[0]))

    eng_d = Engine(cfg, dq, ecfg=ecfg)
    td = [eng_d.admit(0, prompt, opts)]
    for _ in range(5):
        td.append(int(eng_d.decode()[0]))

    assert tq == td


def test_quantized_bytes_halved():
    cfg = tiny()
    params = jax.tree_util.tree_map(
        np.asarray, decoder.init_params(cfg, jax.random.PRNGKey(0)))
    dense = Q.quantized_bytes(params)
    qp = Q.quantize_params(params)
    quant = Q.quantized_bytes(qp)
    assert quant < 0.75 * dense


# ---------------------------------------------------------------------------
# int4 (W4A16, packed nibbles — ops/quant.py int4 section)
# ---------------------------------------------------------------------------

def test_int4_pack_unpack_roundtrip():
    q = rng.integers(-7, 8, (96, 24)).astype(np.int8)
    packed = Q.pack_int4(q)
    assert packed.dtype == np.uint8
    assert packed.shape == (48, 24)
    np.testing.assert_array_equal(Q.unpack_int4(packed), q)


def test_int4_quantize_dequantize_error_bound():
    w = rng.standard_normal((64, 48)).astype(np.float32)
    qw = Q.quantize_groupwise_int4(w)
    assert qw["q4"].shape == (32, 48)
    assert qw["s"].shape == (2, 48)
    back = np.asarray(Q.dequantize_groupwise(qw))
    err = np.abs(back - w)
    step = np.abs(w).reshape(2, 32, 48).max(1, keepdims=True) / 7.0
    assert (err.reshape(2, 32, 48) <= 0.51 * step + 1e-7).all()


def test_int4_grid_is_lossless():
    """Weights already on the symmetric int4 g=32 grid requantize exactly
    (what a GGUF q4_0 tensor dequantizes to, modulo its lone -8 code)."""
    q = rng.integers(-7, 8, (64, 16)).astype(np.int8)
    q.reshape(2, 32, 16)[:, 0, :] = 7      # every group attains ±7
    s = (rng.random((2, 16)).astype(np.float32) + 0.5) / 7.0
    w = np.asarray(Q.dequantize_groupwise({"q4": Q.pack_int4(q), "s": s}))
    qw = Q.quantize_groupwise_int4(w)
    back = np.asarray(Q.dequantize_groupwise(qw))
    np.testing.assert_allclose(back, w, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize("lead", [(3,), (2, 5), (24,)])
def test_qmm4_matches_dequant_matmul(lead):
    """Covers both the decode grouped form (N<=16) and the prefill
    dequant-transient form (N>16)."""
    x = jnp.asarray(rng.standard_normal((*lead, 64)), jnp.float32)
    w = rng.standard_normal((64, 48)).astype(np.float32)
    qw = jax.tree_util.tree_map(jnp.asarray, Q.quantize_groupwise_int4(w))
    want = np.asarray(x) @ np.asarray(Q.dequantize_groupwise(qw))
    got = Q.qmm4(x, qw)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("B,K,O", [(1, 64, 128), (8, 256, 256), (5, 128, 384)])
def test_qmm4_pallas_interpret_matches_xla(B, K, O):
    from ollama_operator_tpu.ops.pallas.quant import qmm4_pallas
    x = jnp.asarray(rng.standard_normal((B, K)), jnp.float32)
    w = rng.standard_normal((K, O)).astype(np.float32)
    qw = jax.tree_util.tree_map(jnp.asarray, Q.quantize_groupwise_int4(w))
    ref = Q.qmm4(x, qw, out_dtype=jnp.float32)
    got = qmm4_pallas(x, qw["q4"], qw["s"], interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_qmm4_pallas_fallback_odd_shapes():
    from ollama_operator_tpu.ops.pallas.quant import qmm4_pallas
    x = jnp.asarray(rng.standard_normal((2, 96)), jnp.float32)
    w = rng.standard_normal((96, 40)).astype(np.float32)
    qw = jax.tree_util.tree_map(jnp.asarray, Q.quantize_groupwise_int4(w))
    ref = Q.qmm4(x, qw, out_dtype=jnp.float32)
    got = qmm4_pallas(x, qw["q4"], qw["s"], interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_int4_decoder_close_to_dense():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = Q.quantize_params(
        jax.tree_util.tree_map(np.asarray, params), bits=4)
    qparams = jax.tree_util.tree_map(jnp.asarray, qparams)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0,
                                cfg.vocab_size)
    ref, _, _ = decoder.prefill_chunk(params, cfg, tokens)
    got, _, _ = decoder.prefill_chunk(qparams, cfg, tokens)
    # int4 drifts more than int8; ranking must still broadly agree
    ref_n, got_n = np.asarray(ref), np.asarray(got)
    assert np.abs(ref_n - got_n).max() < 0.4 * np.abs(ref_n).max() + 0.1
    agree = (ref_n.argmax(-1) == got_n.argmax(-1)).mean()
    assert agree > 0.75


def test_int4_params_tp_sharded_matches_single_device():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = jax.tree_util.tree_map(
        jnp.asarray, Q.quantize_params(jax.tree_util.tree_map(
            np.asarray, params), bits=4))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    ref, _, _ = decoder.prefill_chunk(qparams, cfg, tokens)

    mesh = make_mesh(MeshPlan(tp=4))
    with set_mesh_compat(mesh):
        sharded = shard_params(qparams, mesh, cfg)
        fn = jax.jit(lambda p, t: decoder.prefill_chunk(p, cfg, t))
        out, _, _ = fn(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_engine_int4_params_decode():
    """Engine end-to-end with int4 weights: greedy tokens match the
    dequantized-dense engine (same numeric path, exact grid)."""
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    qparams_np = Q.quantize_params(
        jax.tree_util.tree_map(np.asarray, params), bits=4)
    dq = {}
    for k, v in qparams_np.items():
        if k == "layers":
            dq[k] = {lk: (Q.dequantize_groupwise(lv) if Q.is_quantized(lv)
                          else jnp.asarray(lv)) for lk, lv in v.items()}
        else:
            dq[k] = (Q.dequantize_groupwise(v) if Q.is_quantized(v)
                     else jnp.asarray(v))
    qparams = jax.tree_util.tree_map(jnp.asarray, qparams_np)

    ecfg = EngineConfig(max_slots=2, max_seq_len=64, min_prefill_bucket=8,
                        cache_dtype=jnp.float32)
    opts = SlotOptions(temperature=0.0)
    prompt = np.asarray(rng.integers(1, cfg.vocab_size, 11), np.int32)

    eng_q = Engine(cfg, qparams, ecfg=ecfg)
    tq = [eng_q.admit(0, prompt, opts)]
    for _ in range(5):
        tq.append(int(eng_q.decode()[0]))

    eng_d = Engine(cfg, dq, ecfg=ecfg)
    td = [eng_d.admit(0, prompt, opts)]
    for _ in range(5):
        td.append(int(eng_d.decode()[0]))

    assert tq == td


def test_int4_bytes_quartered():
    """Per quantized leaf: the packed int4 code array is exactly half the
    int8 one (the tiny preset's dense embeddings would wash this out of a
    whole-tree ratio)."""
    cfg = tiny()
    params = jax.tree_util.tree_map(
        np.asarray, decoder.init_params(cfg, jax.random.PRNGKey(0)))
    q8 = Q.quantize_params(dict(params))["layers"]["wq"]
    params2 = jax.tree_util.tree_map(
        np.asarray, decoder.init_params(cfg, jax.random.PRNGKey(0)))
    q4 = Q.quantize_params(params2, bits=4)["layers"]["wq"]
    assert q4["q4"].nbytes * 2 == q8["q"].nbytes
    assert q4["s"].nbytes == q8["s"].nbytes


def test_int4_mm_kernels_interpret_matches_xla():
    """cfg.mm_kernels routes just the quantized matmuls through the
    kernel (decoder._mm); interpret-mode output must match the XLA path."""
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = jax.tree_util.tree_map(
        jnp.asarray, Q.quantize_params(jax.tree_util.tree_map(
            np.asarray, params), bits=4))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0,
                                cfg.vocab_size)
    ref, _, _ = decoder.prefill_chunk(qparams, cfg, tokens)
    import dataclasses
    cfg_k = dataclasses.replace(cfg, mm_kernels="interpret")
    got, _, _ = decoder.prefill_chunk(qparams, cfg_k, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
