"""int8 KV cache: quantized attention vs dense reference, cached forward
parity, and the engine running end-to-end with a quantized cache."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.ops import attention as A
from ollama_operator_tpu.ops import quant_cache as QC
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

rng = np.random.default_rng(31)
F32 = jnp.float32


def tiny(**kw):
    base = cfglib.PRESETS["tiny"]
    return cfglib.ModelConfig(**{**base.__dict__, **kw}).validate()


def test_quantize_kv_roundtrip():
    x = jnp.asarray(rng.standard_normal((2, 4, 8, 16)), F32)
    q, s = QC.quantize_kv(x)
    back = q.astype(F32) * s[..., None]
    err = np.abs(np.asarray(back - x))
    bound = np.asarray(s)[..., None] * 0.51 + 1e-7
    assert (err <= bound).all()


def test_attend_hf_q_matches_dense():
    B, T, S, H, KvH, hd = 2, 1, 32, 8, 2, 16
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), F32) * 0.3
    k = jnp.asarray(rng.standard_normal((B, KvH, S, hd)), F32) * 0.3
    v = jnp.asarray(rng.standard_normal((B, KvH, S, hd)), F32) * 0.3
    mask = A.causal_mask(T, S, 20)
    mask = jnp.broadcast_to(mask, (B, 1, T, S))
    scale = hd ** -0.5

    ref = A.attend_hf(q, k, v, mask, scale)
    kq, ks = QC.quantize_kv(k)
    vq, vs = QC.quantize_kv(v)
    got = QC.attend_hf_q(q, {"q": kq, "s": ks}, {"q": vq, "s": vs},
                         mask, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=0.05, atol=0.02)


def test_attend_hf_q_attn_len():
    """Slots beyond attn_len must not affect the output (garbage there)."""
    B, T, S, H, KvH, hd = 1, 1, 16, 4, 2, 8
    q = jnp.asarray(rng.standard_normal((B, T, H, hd)), F32)
    k = jnp.asarray(rng.standard_normal((B, KvH, S, hd)), F32)
    v = jnp.asarray(rng.standard_normal((B, KvH, S, hd)), F32)
    kq, ks = QC.quantize_kv(k)
    vq, vs = QC.quantize_kv(v)
    # poison the tail
    kq2 = kq.at[:, :, 8:].set(127)
    ks2 = ks.at[:, :, 8:].set(1e6)
    mask = jnp.broadcast_to(A.causal_mask(T, 8, 5), (B, 1, T, 8))
    a = QC.attend_hf_q(q, {"q": kq, "s": ks}, {"q": vq, "s": vs},
                       mask, 0.35, attn_len=8)
    b = QC.attend_hf_q(q, {"q": kq2, "s": ks2}, {"q": vq, "s": vs},
                       mask, 0.35, attn_len=8)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_forward_with_cache_quantized_close_to_dense():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    B, T, split, S = 2, 12, 8, 32
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                cfg.vocab_size)
    ref_logits, _, _ = decoder.prefill_chunk(params, cfg, tokens)

    logits_p, ks, vs = decoder.prefill_chunk(params, cfg, tokens[:, :split])
    qc = QC.empty_cache(cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim)
    kq, ksc = QC.quantize_kv(ks)
    vq, vsc = QC.quantize_kv(vs)
    k_cache = {"q": qc["q"].at[:, :, :, :split].set(kq),
               "s": qc["s"].at[:, :, :, :split].set(ksc)}
    v_cache = {"q": qc["q"].at[:, :, :, :split].set(vq),
               "s": qc["s"].at[:, :, :, :split].set(vsc)}
    lengths = jnp.full((B,), split, jnp.int32)

    logits_d, k_cache, v_cache = decoder.forward_with_cache(
        params, cfg, tokens[:, split:split + 1], k_cache, v_cache, lengths)
    ref_row = np.asarray(ref_logits[:, split])
    got_row = np.asarray(logits_d[:, 0])
    # int8 KV: small drift, ranking preserved
    assert np.abs(got_row - ref_row).max() < 0.1 * np.abs(ref_row).max() + 0.05
    assert (got_row.argmax(-1) == ref_row.argmax(-1)).all()


def test_engine_int8_cache_end_to_end():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(2), dtype=F32)
    ecfg = EngineConfig(max_slots=2, max_seq_len=64, min_prefill_bucket=8,
                        cache_dtype=jnp.int8, decode_chunk=4)
    eng = Engine(cfg, params, ecfg=ecfg)
    opts = SlotOptions(temperature=0.0)
    prompt = np.asarray(rng.integers(1, cfg.vocab_size, 11), np.int32)
    t0 = eng.admit(0, prompt, opts)
    toks = eng.decode_n()
    assert toks.shape == (4, 2)
    assert eng.slot_length(0) == 11 + 4

    # cache footprint ~= half of bf16 (int8 + per-(pos, head) f32 scales;
    # at the toy hd=16 the scales are 1/4 of q — at real hd=128 they are
    # 1/64, so production ratio is ~0.51)
    dense_bytes = (2 * cfg.n_layers * 2 * cfg.n_kv_heads * 64
                   * cfg.head_dim * 2)
    assert eng.kv_bytes <= 0.63 * dense_bytes

    # greedy continuation mostly tracks the bf16-cache engine
    eng2 = Engine(cfg, params, ecfg=EngineConfig(
        max_slots=2, max_seq_len=64, min_prefill_bucket=8,
        cache_dtype=F32, decode_chunk=4))
    t0b = eng2.admit(0, prompt, opts)
    assert t0 == t0b  # first token comes from the dense prefill either way


def test_engine_int8_cache_bucket_crossing():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(3), dtype=F32)
    ecfg = EngineConfig(max_slots=2, max_seq_len=128, min_prefill_bucket=8,
                        cache_dtype=jnp.int8, decode_chunk=4)
    eng = Engine(cfg, params, ecfg=ecfg)
    eng.admit(0, np.arange(1, 7, dtype=np.int32), SlotOptions(temperature=0))
    for _ in range(7):
        eng.decode_n()
    assert eng.slot_length(0) == 6 + 28
    assert eng._attn_bucket(1) >= 32
