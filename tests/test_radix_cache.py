"""Radix prefix cache: page-granular, refcounted cross-request KV reuse.

ISSUE 4 coverage: PageTable refcount/pin accounting + the check()
invariant, RadixCache match/insert/LRU-evict semantics, stitched-vs-cold
stream parity across tail buckets (greedy AND derived-seed sampling),
copy-on-write divergence on a shared boundary page, LRU eviction under
pool pressure, refcounts across preempt-readmit and supervised restart,
and the pages.alloc chaos drill (injected exhaustion mid-stitched
admission falls back to a cold prefill with no leaked pages).
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.models.config import PRESETS
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.paged import PageTable
from ollama_operator_tpu.runtime.radix import RadixCache
from ollama_operator_tpu.runtime.scheduler import Scheduler
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

BASE = PRESETS["tiny"]
XLA = dataclasses.replace(BASE, kernels="xla")
GREEDY = SlotOptions(temperature=0.0)
DENSE = EngineConfig(max_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
                     min_prefill_bucket=16)
PAGED = dataclasses.replace(DENSE, paged=True, page_size=8)

PREFIX = np.arange(1, 25, dtype=np.int32)          # 24 tokens = 3 pages
PROMPT = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(BASE, jax.random.key(0), jnp.float32)


def _gen(eng, slot, full, opts, n):
    """Cold admission + n decode steps on one slot (slot left active)."""
    first = eng.admit(slot, np.asarray(full, np.int32), opts)
    return [first] + [int(eng.decode()[slot]) for _ in range(n)]


def _drain(sched, deadline_s=5.0):
    # quiescent = no active slots AND the epoch-fence quarantine drained
    # (the idle scheduler loop unfences within one iteration; free-page
    # assertions below would otherwise race the last dispatch's frees)
    t1 = time.monotonic() + deadline_s
    while ((sched.n_active or sched.engine.quarantined_pages)
           and time.monotonic() < t1):
        time.sleep(0.01)
    assert sched.n_active == 0
    assert sched.engine.quarantined_pages == 0


# ---------------------------------------------------------------------------
# host accounting units (no engine)
# ---------------------------------------------------------------------------

def test_page_table_refcounts_shared_and_pinned():
    pt = PageTable(n_slots=3, n_pages=6, page_size=8, max_blocks=8)
    assert pt.grow(0, 16)                      # 2 private pages, rc=1
    pages = pt.slot_pages(0)
    pt.pin(pages[0])
    pt.pin(pages[1])                           # the tree adopts both
    pt.release(0)
    assert pt.n_free == 3                      # pinned pages stay resident
    pt.map_shared(1, pages)                    # stitched read-only
    pt.map_shared(2, pages[:1])
    assert pt.shared_refs(pages[0]) == 2
    assert pt.shared_refs(pages[1]) == 1
    pt.check()
    pt.release(1)
    pt.release(2)
    assert pt.n_free == 3                      # pins still hold them
    pt.unpin(pages[0])
    pt.unpin(pages[1])
    assert pt.n_free == 5                      # rc hit zero -> pool
    pt.check()


def test_page_table_check_catches_a_leak():
    pt = PageTable(n_slots=1, n_pages=4, page_size=8, max_blocks=4)
    assert pt.grow(0, 8)
    pt.check()
    # simulate a lost mapping without the matching decref
    pt._owned[0].clear()
    pt.tables[0, :] = 0
    with pytest.raises(AssertionError):
        pt.check()
    pt._free.append(1)  # restore sanity for the autouse sweep
    pt._rc[1] = 0


def test_page_table_alloc_fault_is_a_dry_pool():
    pt = PageTable(n_slots=2, n_pages=5, page_size=8, max_blocks=8)
    FAULTS.arm("pages.alloc", "fail:once")
    assert not pt.grow(0, 8)                   # injected exhaustion
    assert pt.owned_blocks(0) == 0 and pt.n_free == 4
    assert pt.grow(0, 8)                       # disarmed after :once
    pt.check()
    pt.release(0)


def test_radix_match_insert_evict_lru():
    rc = RadixCache(page_size=4)
    ids = list(range(1, 13))                   # 3 chunks
    assert [n.page for n in rc.insert(ids, [10, 11, 12])] == [10, 11, 12]
    assert rc.n_nodes == 3
    assert rc.insert(ids, [20, 21, 22]) == []  # dedup keeps tree pages
    full, part, q = rc.match(ids + [99], 12, bump=False)
    assert [n.page for n in full] == [10, 11, 12] and part is None and q == 0
    # partial boundary: 6 shared tokens = 1 full chunk + 2 into the next
    full, part, q = rc.match(ids[:6] + [77, 78], 8)
    assert [n.page for n in full] == [10] and part.page == 11 and q == 2
    # LRU: a second branch, then bump the first -> branch leaf is oldest
    assert [n.page for n in rc.insert(ids[:4] + [50, 51, 52, 53], [13, 14])
            ] == [14]
    rc.match(ids, 12)
    assert rc.evict(1, lambda pg: True) == [14]
    # page-by-page: children leave before parents
    assert rc.evict(10, lambda pg: True) == [12, 11, 10]
    assert rc.n_nodes == 0
    rc.insert(ids, [10, 11, 12])
    assert sorted(rc.reset()) == [10, 11, 12] and rc.n_nodes == 0


# ---------------------------------------------------------------------------
# engine: stitch / donate / COW parity
# ---------------------------------------------------------------------------

def test_stitch_matches_cold_across_buckets(params):
    """Stitched admission must be bit-identical to a cold prefill for
    greedy AND derived-seed sampling, across tail buckets (16 and 32).
    Same slot + same n_total -> same PRNG seed, so only the KV reuse
    differs between the two paths."""
    eng = Engine(XLA, params, ecfg=PAGED)
    assert eng.radix_enabled
    seeded = SlotOptions(temperature=0.9, top_k=40)
    tails = [np.array([70], np.int32),                 # tail bucket 16
             np.arange(90, 110, dtype=np.int32)]       # tail bucket 32
    cold = {}
    for t in tails:
        full = np.concatenate([PREFIX, t])
        for opts in (GREEDY, seeded):
            cold[(len(t), opts is GREEDY)] = _gen(eng, 0, full, opts, 3)
            eng.release(0)                             # no donation: cold
    assert eng.radix_nodes == 0
    donor = np.concatenate([PREFIX, np.array([60, 61], np.int32)])
    toks = _gen(eng, 0, donor, GREEDY, 2)
    eng.donate_prefix(0, list(donor) + toks[:-1])
    assert eng.radix_nodes == 3                        # the PREFIX chunks
    for t in tails:
        full = np.concatenate([PREFIX, t])
        for opts in (GREEDY, seeded):
            want = eng.prefix_probe(full)
            assert want >= 24
            got = eng.stitch(0, full, want)
            assert got >= 24
            first = eng.extend(0, full, got, opts)
            out = [first] + [int(eng.decode()[0]) for _ in range(3)]
            assert out == cold[(len(t), opts is GREEDY)], (len(t), opts)
            eng.release(0)


def test_cow_divergence_on_shared_boundary(params):
    """A request diverging INSIDE a cached page gets a private copy: its
    stream matches a cold run of the divergent prompt, and the tree's
    page still serves the original continuation bit-identically."""
    eng = Engine(XLA, params, ecfg=PAGED)
    donor = np.arange(1, 29, dtype=np.int32)           # 28 tokens
    toks = _gen(eng, 0, donor, GREEDY, 6)
    donated = list(donor) + toks[:-1]                  # 34 -> 4 full pages
    eng.donate_prefix(0, donated)
    assert eng.radix_nodes == 4
    div = np.asarray(donated[:28] + [77, 78, 79], np.int32)
    want = eng.prefix_probe(div)
    assert want == 28                       # 3 full pages + 4-token partial
    got = eng.stitch(0, div, want)
    assert got == 28
    first = eng.extend(0, div, got, GREEDY)
    out_div = [first] + [int(eng.decode()[0]) for _ in range(3)]
    eng.release(0)
    cold = _gen(eng, 1, div, GREEDY, 3)
    eng.release(1)
    assert out_div == cold
    # the divergent writer copied before writing: replaying the DONOR's
    # exact prompt through the (partially re-shared) tree still yields
    # the donor's original tokens
    want = eng.prefix_probe(donor)
    got = eng.stitch(0, donor, want)
    assert got == 27                        # 24 full + 3 into page 3 (COW)
    first = eng.extend(0, donor, got, GREEDY)
    replay = [first] + [int(eng.decode()[0]) for _ in range(2)]
    assert replay == toks[:3]
    eng.release(0)


# ---------------------------------------------------------------------------
# scheduler: hits, eviction, preemption, restart
# ---------------------------------------------------------------------------

def test_scheduler_radix_hits_shared_prefix_concurrently(params):
    """N requests sharing a prefix all hit the tree (the parked-slot
    design served exactly one), streams stay bit-identical, and the
    hit/miss token counters add up."""
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng)
    try:
        full = np.concatenate([PREFIX, np.array([70, 71, 72], np.int32)])
        out1 = list(sched.submit(full, max_tokens=4, opts=GREEDY).tokens())
        h0 = METRICS.get("tpu_model_prefix_hit_tokens_total")
        r2 = sched.submit(full, max_tokens=4, opts=GREEDY)
        r3 = sched.submit(np.concatenate([PREFIX, [90, 91]]),
                          max_tokens=4, opts=GREEDY)
        assert list(r2.tokens()) == out1
        assert len(list(r3.tokens())) == 4
        assert r2.error is None and r3.error is None
        assert r2.stats.n_reused >= 16
        assert r3.stats.n_reused >= 16      # concurrent second consumer
        hits = METRICS.get("tpu_model_prefix_hit_tokens_total") - h0
        assert hits == r2.stats.n_reused + r3.stats.n_reused
    finally:
        sched.shutdown()


def test_min_prefix_reuse_env_knob(params, monkeypatch):
    """TPU_MIN_PREFIX_REUSE floors radix stitches exactly like parked
    reuse: a floor above the shared prefix forces cold admissions."""
    monkeypatch.setenv("TPU_MIN_PREFIX_REUSE", "48")
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng)
    try:
        assert sched.min_prefix_reuse == 48
        full = np.concatenate([PREFIX, np.array([70, 71], np.int32)])
        list(sched.submit(full, max_tokens=4, opts=GREEDY).tokens())
        r2 = sched.submit(full, max_tokens=4, opts=GREEDY)
        list(r2.tokens())
        assert r2.stats.n_reused == 0       # 25 matchable < 48 floor
    finally:
        sched.shutdown()


def test_radix_lru_eviction_under_pressure(params):
    """A pool smaller than the working set: donations keep pinning pages
    until admissions run dry, eviction trims LRU leaves page-by-page,
    and every request still finishes with its full budget."""
    eng = Engine(XLA, params, ecfg=dataclasses.replace(
        PAGED, max_slots=2, n_pages=8))
    sched = Scheduler(eng)
    try:
        outs = []
        for i in range(4):
            prompt = np.arange(1 + 20 * i, 17 + 20 * i, dtype=np.int32)
            r = sched.submit(prompt, max_tokens=4, opts=GREEDY)
            outs.append(list(r.tokens()))
            assert r.error is None
        assert all(len(o) == 4 for o in outs)
        _drain(sched)
        # 4 donations x 2 pages > the 8-page pool: eviction must have run
        assert 0 < eng.radix_pages <= 6
        assert eng.free_pages == eng._pt.data_pages - eng.radix_pages
        eng._pt.check()
    finally:
        sched.shutdown()


def test_refcounts_across_preempt_readmit(params):
    """Pool pressure with concurrent requests: preempted requests resume
    on the same stream with full budgets, and when the dust settles every
    page is either free or pinned by the tree — no refcount drift."""
    eng = Engine(XLA, params, ecfg=dataclasses.replace(
        PAGED, max_slots=3, n_pages=6))
    sched = Scheduler(eng)
    try:
        reqs = [sched.submit(PROMPT + i, max_tokens=12, opts=GREEDY)
                for i in range(3)]
        outs = [list(r.tokens()) for r in reqs]
        for r, out in zip(reqs, outs):
            assert r.error is None
            assert len(out) == 12, (len(out), r.error)
        assert sched.n_preemptions >= 1
        _drain(sched)
        assert eng.free_pages == eng._pt.data_pages - eng.radix_pages
        eng._pt.check()
    finally:
        sched.shutdown()


@pytest.mark.chaos
def test_radix_reset_on_supervised_restart(params, monkeypatch):
    """A decode-loop failure rebuilds the engine state: the tree must be
    dropped with it (its cache contents are unknown) and its pins
    returned, then serving continues and re-populates the cache."""
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng, restart_backoff=0.001)
    try:
        r1 = sched.submit(PROMPT, max_tokens=6, opts=GREEDY)
        assert len(list(r1.tokens())) == 6
        assert eng.radix_nodes >= 1          # donated on finish
        FAULTS.arm("engine.step", "fail:once")
        r2 = sched.submit(PROMPT + 1, max_tokens=6, opts=GREEDY)
        with pytest.raises(RuntimeError):
            list(r2.tokens())
        t1 = time.monotonic() + 5
        while sched.n_restarts < 1 and time.monotonic() < t1:
            time.sleep(0.01)
        assert sched.n_restarts >= 1 and not sched.broken
        assert eng.radix_nodes == 0
        assert eng.free_pages == eng._pt.data_pages   # nothing pinned
        r3 = sched.submit(PROMPT, max_tokens=6, opts=GREEDY)
        assert len(list(r3.tokens())) == 6
        assert eng.radix_nodes >= 1          # cache re-populates
    finally:
        sched.shutdown()


@pytest.mark.chaos
def test_pages_alloc_fault_mid_stitch_falls_back_cold(params):
    """CI chaos drill (ISSUE 4): inject pool exhaustion into the
    copy-on-write allocation of a stitched admission. The admission must
    fall back to a cold prefill with a bit-identical stream, and no page
    may leak (free + tree-pinned covers the whole pool)."""
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng)
    try:
        full = np.concatenate([PREFIX, np.array([70, 71, 72], np.int32)])
        out1 = list(sched.submit(full, max_tokens=4, opts=GREEDY).tokens())
        assert eng.prefix_probe(full) >= 16  # a stitch WOULD hit
        FAULTS.arm("pages.alloc", "fail:once")
        r2 = sched.submit(full, max_tokens=4, opts=GREEDY)
        out2 = list(r2.tokens())
        assert r2.error is None
        assert out2 == out1                  # cold fallback, same stream
        assert r2.stats.n_reused == 0        # it really went cold
        _drain(sched)
        assert eng.free_pages == eng._pt.data_pages - eng.radix_pages
        eng._pt.check()
    finally:
        sched.shutdown()
