"""Registry client + blob store against the fake registry."""

import os

import pytest

from ollama_operator_tpu.server.names import ModelName
from ollama_operator_tpu.server.registry import (
    MT_MODEL, ModelStore, RegistryClient, RegistryError)

from fake_registry import FakeRegistry


def test_name_parsing():
    n = ModelName.parse("phi")
    assert (n.registry, n.namespace, n.name, n.tag) == (
        "registry.ollama.ai", "library", "phi", "latest")
    assert n.short == "phi:latest"
    n2 = ModelName.parse("myuser/mymodel:7b")
    assert n2.namespace == "myuser" and n2.tag == "7b"
    n3 = ModelName.parse("http://127.0.0.1:5000/library/m:t")
    assert n3.base_url == "http://127.0.0.1:5000"
    assert n3.manifest_url() == "http://127.0.0.1:5000/v2/library/m/manifests/t"


@pytest.fixture()
def registry():
    r = FakeRegistry()
    url = r.start()
    yield r, url
    r.stop()


def test_pull_and_list(tmp_path, registry):
    reg, url = registry
    reg.add_model("library", "m", "latest", b"GGUF-bytes-here" * 100,
                  template="{{ .Prompt }}", params={"temperature": 0.5})
    store = ModelStore(str(tmp_path))
    client = RegistryClient(store)
    name = client.pull(f"{url}/library/m:latest")
    assert store.read_manifest(name) is not None
    layers = store.model_layers(name)
    assert MT_MODEL in layers
    with open(layers[MT_MODEL], "rb") as f:
        assert f.read() == b"GGUF-bytes-here" * 100
    models = store.list_models()
    assert len(models) == 1


def test_pull_is_idempotent(tmp_path, registry):
    reg, url = registry
    reg.add_model("library", "m", "latest", b"x" * 1000)
    store = ModelStore(str(tmp_path))
    client = RegistryClient(store)
    client.pull(f"{url}/library/m:latest")
    n_before = len([r for r in reg.requests if "blobs" in r[1]])
    client.pull(f"{url}/library/m:latest")
    n_after = len([r for r in reg.requests if "blobs" in r[1]])
    assert n_after == n_before  # cached blobs are not re-fetched


def test_pull_resumes_partial(tmp_path, registry):
    reg, url = registry
    data = b"z" * 5000
    entry = reg.add_model("library", "m", "latest", data)
    store = ModelStore(str(tmp_path))
    client = RegistryClient(store)
    # simulate an interrupted download
    import hashlib
    digest = "sha256:" + hashlib.sha256(data).hexdigest()
    partial = store.blob_path(digest) + ".partial"
    with open(partial, "wb") as f:
        f.write(data[:2000])
    # abandoned partials are only claimed once stale (a live writer keeps
    # mtime fresh); backdate to simulate a crashed puller
    import os as _os
    _os.utime(partial, (1, 1))
    client.pull(f"{url}/library/m:latest")
    with open(store.blob_path(digest), "rb") as f:
        assert f.read() == data
    range_reqs = [r for r in reg.requests
                  if r[2].get("Range") == "bytes=2000-"]
    assert range_reqs, "client did not resume with a Range request"


def test_digest_verification(tmp_path, registry):
    reg, url = registry
    reg.add_model("library", "m", "latest", b"good")
    # corrupt the stored blob server-side
    for d in list(reg.blobs):
        if reg.blobs[d] == b"good":
            reg.blobs[d] = b"evil"
    store = ModelStore(str(tmp_path))
    client = RegistryClient(store)
    with pytest.raises(RegistryError, match="digest mismatch"):
        client.pull(f"{url}/library/m:latest")


def test_missing_model_404(tmp_path, registry):
    reg, url = registry
    store = ModelStore(str(tmp_path))
    client = RegistryClient(store)
    with pytest.raises(RegistryError, match="not found"):
        client.pull(f"{url}/library/nope:latest")


def test_delete_and_gc(tmp_path, registry):
    reg, url = registry
    reg.add_model("library", "m", "latest", b"blobdata")
    store = ModelStore(str(tmp_path))
    client = RegistryClient(store)
    name = client.pull(f"{url}/library/m:latest")
    blob_dir = os.path.join(str(tmp_path), "blobs")
    assert len(os.listdir(blob_dir)) > 0
    assert store.delete_model(name)
    assert len(os.listdir(blob_dir)) == 0  # gc removed unreferenced blobs
    assert not store.delete_model(name)
