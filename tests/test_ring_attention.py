"""Ring attention / sequence-parallel long context on the 8-device CPU mesh.

The sequence axis is new TPU-native capability (SURVEY.md §5: the reference
has no long-context support at all) — these tests pin its semantics to the
dense single-device decoder bit-approximately."""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.ops.attention import (attend_hf, causal_mask,
                                               shard_map_compat)
from ollama_operator_tpu.parallel import MeshPlan, make_mesh, shard_params
from ollama_operator_tpu.parallel import long_context as lc
from ollama_operator_tpu.parallel.ring_attention import (
    ring_attention, sp_cache_write, sp_decode_attention)
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

F32 = jnp.float32


def tiny():
    return cfglib.PRESETS["tiny"]


def _ring_dense_pair(sp, T=32, window=0, seed=0):
    """Run ring_attention on an sp-way mesh and dense attend_hf; return both."""
    mesh = make_mesh(MeshPlan(dp=1, sp=sp, tp=8 // sp))
    B, H, KvH, hd = 2, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, H, hd), F32)
    k = jax.random.normal(ks[1], (B, KvH, T, hd), F32)
    v = jax.random.normal(ks[2], (B, KvH, T, hd), F32)
    scale = 1.0 / math.sqrt(hd)

    mask = causal_mask(T, T, 0, sliding_window=window)
    mask = jnp.broadcast_to(mask, (B, 1, T, T))
    ref = attend_hf(q, k, v, mask, scale)

    fn = jax.jit(shard_map_compat(
        lambda q, k, v: ring_attention(q, k, v, scale, "sp",
                                       sliding_window=window),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, None, "sp"), P(None, None, "sp")),
        out_specs=P(None, "sp"),
        axis_names={"sp"}))
    out = fn(q, k, v)
    return np.asarray(ref), np.asarray(out)


def test_ring_attention_matches_dense_causal():
    for sp in (2, 4, 8):
        ref, out = _ring_dense_pair(sp, seed=sp)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_ring_attention_sliding_window():
    ref, out = _ring_dense_pair(4, T=32, window=9, seed=3)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_sp_decode_attention_matches_dense():
    mesh = make_mesh(MeshPlan(dp=1, sp=4, tp=2))
    B, T, H, KvH, hd, S = 3, 1, 4, 2, 16, 32
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (B, T, H, hd), F32)
    kc = jax.random.normal(ks[1], (B, KvH, S, hd), F32)
    vc = jax.random.normal(ks[2], (B, KvH, S, hd), F32)
    lengths = jnp.array([5, 17, 32], jnp.int32)
    q_pos = (lengths - 1)[:, None]
    scale = 1.0 / math.sqrt(hd)

    k_pos = jnp.arange(S)[None, None, :]
    mask = jnp.where(k_pos <= q_pos[:, :, None], 0.0, -1e30)[:, None]
    ref = attend_hf(q, kc, vc, mask, scale)

    fn = jax.jit(shard_map_compat(
        lambda q, kc, vc, qp: sp_decode_attention(q, kc, vc, qp, scale, "sp"),
        mesh=mesh,
        in_specs=(P(), P(None, None, "sp"), P(None, None, "sp"), P()),
        out_specs=P(),
        axis_names={"sp"}))
    out = fn(q, kc, vc, q_pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


def test_sp_cache_write_places_tokens_on_owner():
    # T=2 writes straddling a shard boundary (chunk size 4: positions 3|4
    # and 12|13 land on different owners) — exercises the mode="drop"
    # scatter contract for multi-token chunked continuation.
    mesh = make_mesh(MeshPlan(dp=1, sp=4, tp=2))
    B, KvH, S, hd, T = 2, 2, 16, 8, 2
    kc = jnp.zeros((B, KvH, S, hd), F32)
    vc = jnp.zeros((B, KvH, S, hd), F32)
    vals = jnp.array([[[[2.0]], [[2.5]]], [[[3.0]], [[3.5]]]])  # [B,T,1,1]
    k_new = jnp.ones((B, KvH, T, hd), F32) * vals.transpose(0, 2, 1, 3)
    pos = jnp.array([[3, 4], [12, 13]], jnp.int32)

    fn = jax.jit(shard_map_compat(
        lambda kc, vc, kn, vn, p: sp_cache_write(kc, vc, kn, vn, p, "sp"),
        mesh=mesh,
        in_specs=(P(None, None, "sp"), P(None, None, "sp"), P(), P(), P()),
        out_specs=(P(None, None, "sp"), P(None, None, "sp")),
        axis_names={"sp"}))
    kc2, _ = fn(kc, vc, k_new, k_new, pos)
    got = np.asarray(kc2)
    assert np.all(got[0, :, 3] == 2.0) and np.all(got[0, :, 4] == 2.5)
    assert np.all(got[1, :, 12] == 3.0) and np.all(got[1, :, 13] == 3.5)
    mask = np.ones((B, S), bool)
    mask[0, 3] = mask[0, 4] = mask[1, 12] = mask[1, 13] = False
    assert np.all(got.transpose(0, 2, 1, 3)[mask] == 0.0)


def test_sp_prefill_matches_reference():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                cfg.vocab_size)
    ref, ref_k, ref_v = decoder.prefill_chunk(params, cfg, tokens)

    mesh = make_mesh(MeshPlan(dp=1, sp=4, tp=2))
    sharded = shard_params(params, mesh, cfg)
    out, ks, vs = jax.jit(
        lambda p, t: lc.prefill_chunk_sp(p, cfg, t, mesh))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(ks), np.asarray(ref_k), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(vs), np.asarray(ref_v), rtol=2e-4,
                               atol=2e-4)


def test_sp_forward_with_cache_matches_reference():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    B, S = 2, 32
    shape = (cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim)
    k_cache = jax.random.normal(jax.random.PRNGKey(2), shape, F32)
    v_cache = jax.random.normal(jax.random.PRNGKey(3), shape, F32)
    lengths = jnp.array([9, 21], jnp.int32)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (B, 1), 0,
                                cfg.vocab_size)
    ref, ref_k, ref_v = decoder.forward_with_cache(
        params, cfg, tokens, k_cache, v_cache, lengths)

    mesh = make_mesh(MeshPlan(dp=1, sp=4, tp=2))
    sharded = shard_params(params, mesh, cfg)
    cache_sh = NamedSharding(mesh, P(None, None, None, "sp", None))
    kc = jax.device_put(k_cache, cache_sh)
    vc = jax.device_put(v_cache, cache_sh)
    out, ks, vs = jax.jit(
        lambda p, t, kc, vc, l: lc.forward_with_cache_sp(
            p, cfg, t, kc, vc, l, mesh))(sharded, tokens, kc, vc, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(ks), np.asarray(ref_k), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(vs), np.asarray(ref_v), rtol=2e-4,
                               atol=2e-4)


def test_engine_sp_greedy_matches_single_device():
    from tests.test_engine import GREEDY, greedy_reference

    cfg = dataclasses.replace(tiny(), kernels="xla")
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    ref = greedy_reference(params, cfg, np.array([5, 9, 2, 11, 7], np.int32),
                           6)

    mesh = make_mesh(MeshPlan(dp=1, sp=4, tp=2))
    eng = Engine(cfg, params, mesh=mesh,
                 ecfg=EngineConfig(max_slots=4, max_seq_len=128,
                                   cache_dtype=F32, min_prefill_bucket=16))
    assert eng.sp_size == 4
    got = [eng.admit(0, np.array([5, 9, 2, 11, 7], np.int32), GREEDY)]
    for _ in range(5):
        got.append(int(eng.decode()[0]))
    assert got == ref


def test_engine_sp_int8_matches_single_device_int8():
    """int8 KV × sp (round-1 weak #4 exclusion): the sp collectives
    quantize fresh K/V into sharded {"q","s"} chunks and fold the scales
    into scores/probs — greedy tokens must match the single-device int8
    engine exactly (identical quantization on both sides)."""
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    cfg = dataclasses.replace(tiny(), kernels="xla")
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    prompt = np.array([3, 1, 4, 1, 5, 9, 2, 6, 10, 11, 12, 13], np.int32)
    opts = SlotOptions(temperature=0.0)

    def run(mesh):
        eng = Engine(cfg, params, mesh=mesh,
                     ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                       cache_dtype=jnp.int8,
                                       min_prefill_bucket=16))
        seq = [eng.admit(0, prompt, opts)]
        for _ in range(6):
            seq.append(int(eng.decode()[0]))
        return seq

    assert run(make_mesh(MeshPlan(sp=2, tp=2))) == run(None)


def test_engine_sp_multimodal_embeds_matches_single_device():
    """Multimodal admissions on sp meshes (round-1 weak #4): embeds shard
    over sp along the sequence axis through prefill_chunk_sp."""
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    cfg = dataclasses.replace(tiny(), kernels="xla")
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    prompt = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
    emb = np.asarray(decoder._embed(cfg, params,
                                    jnp.asarray(prompt)[None]))[0]
    opts = SlotOptions(temperature=0.0)

    def run(mesh):
        eng = Engine(cfg, params, mesh=mesh,
                     ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                       cache_dtype=F32,
                                       min_prefill_bucket=16))
        seq = [eng.admit(0, prompt, opts, embeds=emb)]
        for _ in range(3):
            seq.append(int(eng.decode()[0]))
        return seq

    assert run(make_mesh(MeshPlan(sp=2, tp=2))) == run(None)
