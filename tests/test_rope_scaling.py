"""RoPE context-extension scaling (ops/rope.scaled_inv_freq).

The reference serves long-context models through llama.cpp inside the
delegated image (/root/reference/pkg/model/pod.go:11), which honors GGUF
``rope.scaling.*`` metadata (linear / YaRN) and the pre-baked
``rope_freqs.weight`` factor tensor of llama3.1-family conversions. These
tests pin our static per-frequency rescale against transformers'
ROPE_INIT_FUNCTIONS (the ecosystem-canonical math, matching llama.cpp) and
cover the GGUF metadata → ModelConfig plumbing.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from ollama_operator_tpu.gguf import writer as W
from ollama_operator_tpu.gguf.transcode import config_from_gguf
from ollama_operator_tpu.gguf.reader import GGUFFile
from ollama_operator_tpu.models.config import ModelConfig, get_config
from ollama_operator_tpu.ops.rope import (rope_angles, rope_angles_cfg,
                                          scaled_inv_freq)


def test_linear_matches_legacy_position_division():
    pos = jnp.arange(40, dtype=jnp.int32)[None]
    ref_cos, ref_sin = rope_angles(pos, 64, 10000.0, scaling=4.0)
    cfg = ModelConfig(rope_scaling_type="linear", rope_scaling=4.0,
                      head_dim=64).validate()
    got_cos, got_sin = rope_angles_cfg(pos, cfg)
    np.testing.assert_allclose(np.asarray(got_cos), np.asarray(ref_cos),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got_sin), np.asarray(ref_sin),
                               rtol=1e-6, atol=1e-6)


def test_none_type_honors_legacy_bare_factor():
    # back-compat: old configs carried rope_scaling as a bare linear factor
    # with no type field
    f_lin, m_lin = scaled_inv_freq(32, 10000.0, scaling_type="linear",
                                   factor=2.0)
    f_leg, m_leg = scaled_inv_freq(32, 10000.0, scaling_type="none",
                                   factor=2.0)
    assert f_lin == f_leg and m_lin == m_leg == 1.0


def test_freq_factors_divide_and_win_over_scheme():
    ff = tuple(float(2 + i) for i in range(16))
    base, _ = scaled_inv_freq(32, 10000.0)
    got, m = scaled_inv_freq(32, 10000.0, scaling_type="linear", factor=8.0,
                             freq_factors=ff)
    assert m == 1.0
    np.testing.assert_allclose(np.array(got),
                               np.array(base) / np.array(ff), rtol=1e-6)


def _hf_rope(rope_scaling: dict, head_dim=32, theta=10000.0, max_pos=4096):
    transformers = pytest.importorskip("transformers")
    torch = pytest.importorskip("torch")
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS
    cfg = transformers.LlamaConfig(
        hidden_size=head_dim * 4, num_attention_heads=4,
        max_position_embeddings=max_pos, rope_theta=theta,
        rope_scaling=dict(rope_scaling))
    fn = ROPE_INIT_FUNCTIONS[rope_scaling["rope_type"]]
    inv_freq, attention_scaling = fn(cfg, device=torch.device("cpu"))
    return np.asarray(inv_freq, np.float64), float(attention_scaling)


def test_llama3_matches_transformers():
    spec = {"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 8192}
    ref, ref_m = _hf_rope(spec, head_dim=128, theta=500000.0)
    got, m = scaled_inv_freq(128, 500000.0, scaling_type="llama3",
                             factor=8.0, orig_ctx=8192,
                             low_freq_factor=1.0, high_freq_factor=4.0)
    assert m == ref_m == 1.0
    np.testing.assert_allclose(np.array(got), ref, rtol=1e-6)


def test_llama3_covers_all_three_bands():
    # orig_ctx 32, theta 1e4, hd 16: dim 0 keeps, dim 1 blends, rest scale
    got, _ = scaled_inv_freq(16, 10000.0, scaling_type="llama3", factor=4.0,
                             orig_ctx=32, low_freq_factor=1.0,
                             high_freq_factor=4.0)
    base, _ = scaled_inv_freq(16, 10000.0)
    ratio = np.array(base) / np.array(got)
    assert ratio[0] == pytest.approx(1.0)
    assert 1.0 < ratio[1] < 4.0
    np.testing.assert_allclose(ratio[2:], 4.0, rtol=1e-6)


def test_yarn_matches_transformers():
    spec = {"rope_type": "yarn", "factor": 4.0,
            "original_max_position_embeddings": 2048}
    ref, ref_m = _hf_rope(spec, head_dim=64, theta=10000.0, max_pos=8192)
    got, m = scaled_inv_freq(64, 10000.0, scaling_type="yarn", factor=4.0,
                             orig_ctx=2048)
    assert m == pytest.approx(ref_m)     # 0.1*ln(4)+1
    np.testing.assert_allclose(np.array(got), ref, rtol=1e-6)


def test_yarn_explicit_attention_factor():
    spec = {"rope_type": "yarn", "factor": 4.0, "attention_factor": 1.5,
            "original_max_position_embeddings": 2048}
    _, ref_m = _hf_rope(spec, head_dim=64, theta=10000.0, max_pos=8192)
    _, m = scaled_inv_freq(64, 10000.0, scaling_type="yarn", factor=4.0,
                           orig_ctx=2048, attn_factor=1.5)
    assert m == pytest.approx(ref_m) == pytest.approx(1.5)


def test_presets_llama31_32_scaled():
    for name, factor in (("llama3.1", 8.0), ("llama3.2:1b", 32.0),
                         ("llama3.2:3b", 32.0)):
        cfg = get_config(name)
        assert cfg.rope_scaling_type == "llama3"
        assert cfg.rope_scaling == factor
        assert cfg.rope_orig_ctx == 8192
        assert cfg.max_seq_len == 131072
        # the scheme actually moves the low-frequency rates
        got, _ = scaled_inv_freq(cfg.rotary_dim, cfg.rope_theta,
                                 scaling_type=cfg.rope_scaling_type,
                                 factor=cfg.rope_scaling,
                                 orig_ctx=cfg.rope_orig_ctx)
        base, _ = scaled_inv_freq(cfg.rotary_dim, cfg.rope_theta)
        assert got[-1] == pytest.approx(base[-1] / factor, rel=1e-6)
        assert got[0] == pytest.approx(base[0], rel=1e-6)


# ---------------------------------------------------------------------------
# GGUF metadata plumbing
# ---------------------------------------------------------------------------

def _tiny_gguf(tmp_path, extra_meta=(), extra_tensors=(), name="m.gguf"):
    path = str(tmp_path / name)
    w = W.GGUFWriter(path)
    w.add_meta("general.architecture", "llama")
    w.add_meta("llama.block_count", 1)
    w.add_meta("llama.embedding_length", 16)
    w.add_meta("llama.attention.head_count", 2)
    w.add_meta("llama.attention.head_count_kv", 2)
    w.add_meta("llama.feed_forward_length", 32)
    w.add_meta("llama.context_length", 256)
    w.add_meta("tokenizer.ggml.model", "llama")
    w.add_meta("tokenizer.ggml.tokens", [f"t{i}" for i in range(8)])
    w.add_meta("tokenizer.ggml.scores", [0.0] * 8)
    w.add_meta("tokenizer.ggml.token_type", [1] * 8)
    for k, v in extra_meta:
        w.add_meta(k, v)
    # minimal tensor so tie detection has something to look at
    w.add_tensor_f32("output.weight", np.zeros((8, 16), np.float32))
    for name, arr in extra_tensors:
        w.add_tensor_f32(name, arr)
    w.write()
    return path


def test_gguf_yarn_metadata(tmp_path):
    path = _tiny_gguf(tmp_path, extra_meta=[
        ("llama.rope.scaling.type", "yarn"),
        ("llama.rope.scaling.factor", 4.0),
        ("llama.rope.scaling.original_context_length", 64),
        ("llama.rope.scaling.attn_factor", 1.2)])
    with GGUFFile(path) as f:
        cfg = config_from_gguf(f)
    assert cfg.rope_scaling_type == "yarn"
    assert cfg.rope_scaling == 4.0
    assert cfg.rope_orig_ctx == 64
    assert cfg.rope_attn_factor == pytest.approx(1.2)


def test_gguf_yarn_missing_orig_ctx_falls_back(tmp_path):
    path = _tiny_gguf(tmp_path, extra_meta=[
        ("llama.rope.scaling.type", "yarn"),
        ("llama.rope.scaling.factor", 4.0)])
    with GGUFFile(path) as f:
        cfg = config_from_gguf(f)
    assert cfg.rope_orig_ctx == 64     # context_length 256 / factor 4


def test_gguf_legacy_scale_linear(tmp_path):
    path = _tiny_gguf(tmp_path, extra_meta=[
        ("llama.rope.scale_linear", 2.0)])
    with GGUFFile(path) as f:
        cfg = config_from_gguf(f)
    assert cfg.rope_scaling_type == "linear"
    assert cfg.rope_scaling == 2.0


def test_gguf_rope_freqs_tensor(tmp_path):
    ff = np.linspace(1.0, 8.0, 4).astype(np.float32)
    path = _tiny_gguf(tmp_path, extra_tensors=[("rope_freqs.weight", ff)])
    with GGUFFile(path) as f:
        cfg = config_from_gguf(f)
    assert cfg.rope_freq_factors == tuple(float(x) for x in ff)
    # the factors reach the angle computation
    got, _ = scaled_inv_freq(cfg.rotary_dim, cfg.rope_theta,
                             freq_factors=cfg.rope_freq_factors)
    base, _ = scaled_inv_freq(cfg.rotary_dim, cfg.rope_theta)
    np.testing.assert_allclose(np.array(got), np.array(base) / ff,
                               rtol=1e-6)


def test_gguf_unsupported_scaling_type_fails_loudly(tmp_path):
    # a genuinely unknown scheme is rejected outright
    path = _tiny_gguf(tmp_path, extra_meta=[
        ("llama.rope.scaling.type", "ntk-parts-v9")])
    with GGUFFile(path) as f:
        with pytest.raises(NotImplementedError):
            config_from_gguf(f)
    # longrope is supported (phi3 family, round 5) but ONLY via its
    # rope_factors_* tensors — declaring the type without them must fail
    # loudly, not serve unscaled rope
    path = _tiny_gguf(tmp_path, extra_meta=[
        ("llama.rope.scaling.type", "longrope")], name="lr.gguf")
    with GGUFFile(path) as f:
        with pytest.raises(ValueError, match="rope_factors"):
            config_from_gguf(f)


def test_config_roundtrips_freq_factors_as_json():
    # gguf/store.py meta is JSON: tuples come back as lists; validate()
    # re-coerces so the config stays hashable for jit static args
    import json
    cfg = ModelConfig(rope_freq_factors=(1.0, 2.0, 3.0, 4.0, 5.0, 6.0,
                                         7.0, 8.0),
                      head_dim=16).validate()
    back = ModelConfig(**json.loads(json.dumps(cfg.__dict__))).validate()
    assert back.rope_freq_factors == cfg.rope_freq_factors
    hash(back)   # must stay usable as a jit static
