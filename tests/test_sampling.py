"""Sampler semantics: masks, penalties, greedy/seeded behaviour."""

import jax
import jax.numpy as jnp
import numpy as np

from ollama_operator_tpu.ops import sampling


def mk_sp(B, **kw):
    return sampling.SamplingParams.make(B, **kw)


def test_greedy_when_temperature_zero():
    logits = jnp.array([[0.1, 2.0, -1.0, 0.5]])
    sp = mk_sp(1, temperature=0.0, repeat_penalty=1.0)
    tok = sampling.sample(logits, jnp.zeros((1, 4), jnp.int32), sp,
                          jax.random.key(0))
    assert int(tok[0]) == 1


def test_top_k_restricts_support():
    logits = jnp.array([[0.0, 5.0, 4.0, -2.0, 1.0]])
    sp = mk_sp(1, temperature=1.0, top_k=2, top_p=1.0, repeat_penalty=1.0)
    counts = jnp.zeros((1, 5), jnp.int32)
    seen = set()
    for i in range(50):
        tok = sampling.sample(logits, counts, sp, jax.random.key(i))
        seen.add(int(tok[0]))
    assert seen <= {1, 2}


def test_top_p_keeps_head_of_distribution():
    # one dominant token (p≈0.99) → top_p=0.5 must always pick it
    logits = jnp.array([[10.0, 1.0, 0.0, -1.0]])
    sp = mk_sp(1, temperature=1.0, top_k=0, top_p=0.5, repeat_penalty=1.0)
    counts = jnp.zeros((1, 4), jnp.int32)
    for i in range(20):
        tok = sampling.sample(logits, counts, sp, jax.random.key(i))
        assert int(tok[0]) == 0


def test_repeat_penalty_discourages_seen_tokens():
    logits = jnp.array([[2.0, 1.9]])
    counts = jnp.array([[5, 0]], jnp.int32)  # token 0 was generated already
    sp = mk_sp(1, temperature=0.0, repeat_penalty=2.0)
    tok = sampling.sample(logits, counts, sp, jax.random.key(0))
    assert int(tok[0]) == 1  # 2.0/2.0 = 1.0 < 1.9


def test_per_slot_seeds_reproducible():
    logits = jnp.tile(jnp.array([[0.0, 0.1, 0.2, 0.3]]), (2, 1))
    sp = mk_sp(2, temperature=1.0, top_k=0, top_p=1.0, repeat_penalty=1.0)
    counts = jnp.zeros((2, 4), jnp.int32)
    keys = jnp.stack([jax.random.key(7), jax.random.key(7)])
    t1 = sampling.sample(logits, counts, sp, keys)
    t2 = sampling.sample(logits, counts, sp, keys)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    assert int(t1[0]) == int(t1[1])  # same seed, same logits → same token


def test_frequency_and_presence_penalty():
    logits = jnp.array([[1.0, 0.9]])
    counts = jnp.array([[3, 0]], jnp.int32)
    sp = sampling.SamplingParams.make(1, temperature=0.0, repeat_penalty=1.0,
                                      presence_penalty=0.05,
                                      frequency_penalty=0.05)
    tok = sampling.sample(logits, counts, sp, jax.random.key(0))
    assert int(tok[0]) == 1  # 1.0 - 0.05 - 3*0.05 = 0.8 < 0.9
