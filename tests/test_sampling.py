"""Sampler semantics: masks, penalties, greedy/seeded behaviour."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ollama_operator_tpu.ops import sampling


def mk_sp(B, **kw):
    return sampling.SamplingParams.make(B, **kw)


def test_greedy_when_temperature_zero():
    logits = jnp.array([[0.1, 2.0, -1.0, 0.5]])
    sp = mk_sp(1, temperature=0.0, repeat_penalty=1.0)
    tok = sampling.sample(logits, jnp.zeros((1, 4), jnp.int32), sp,
                          jax.random.key(0))
    assert int(tok[0]) == 1


def test_top_k_restricts_support():
    logits = jnp.array([[0.0, 5.0, 4.0, -2.0, 1.0]])
    sp = mk_sp(1, temperature=1.0, top_k=2, top_p=1.0, repeat_penalty=1.0)
    counts = jnp.zeros((1, 5), jnp.int32)
    seen = set()
    for i in range(50):
        tok = sampling.sample(logits, counts, sp, jax.random.key(i))
        seen.add(int(tok[0]))
    assert seen <= {1, 2}


def test_top_p_keeps_head_of_distribution():
    # one dominant token (p≈0.99) → top_p=0.5 must always pick it
    logits = jnp.array([[10.0, 1.0, 0.0, -1.0]])
    sp = mk_sp(1, temperature=1.0, top_k=0, top_p=0.5, repeat_penalty=1.0)
    counts = jnp.zeros((1, 4), jnp.int32)
    for i in range(20):
        tok = sampling.sample(logits, counts, sp, jax.random.key(i))
        assert int(tok[0]) == 0


def test_repeat_penalty_discourages_seen_tokens():
    logits = jnp.array([[2.0, 1.9]])
    counts = jnp.array([[5, 0]], jnp.int32)  # token 0 was generated already
    sp = mk_sp(1, temperature=0.0, repeat_penalty=2.0)
    tok = sampling.sample(logits, counts, sp, jax.random.key(0))
    assert int(tok[0]) == 1  # 2.0/2.0 = 1.0 < 1.9


def test_per_slot_seeds_reproducible():
    logits = jnp.tile(jnp.array([[0.0, 0.1, 0.2, 0.3]]), (2, 1))
    sp = mk_sp(2, temperature=1.0, top_k=0, top_p=1.0, repeat_penalty=1.0)
    counts = jnp.zeros((2, 4), jnp.int32)
    keys = jnp.stack([jax.random.key(7), jax.random.key(7)])
    t1 = sampling.sample(logits, counts, sp, keys)
    t2 = sampling.sample(logits, counts, sp, keys)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    assert int(t1[0]) == int(t1[1])  # same seed, same logits → same token


def test_frequency_and_presence_penalty():
    logits = jnp.array([[1.0, 0.9]])
    counts = jnp.array([[3, 0]], jnp.int32)
    sp = sampling.SamplingParams.make(1, temperature=0.0, repeat_penalty=1.0,
                                      presence_penalty=0.05,
                                      frequency_penalty=0.05)
    tok = sampling.sample(logits, counts, sp, jax.random.key(0))
    assert int(tok[0]) == 1  # 1.0 - 0.05 - 3*0.05 = 0.8 < 0.9

def test_typical_p_drops_atypical_outliers():
    # wide near-uniform body + one modestly-peaked head: entropy sits at
    # the body's surprise, so the HEAD is the atypical token (its surprise
    # is far below H) — a tight typical_p keeps the body and drops the
    # argmax (locally-typical sampling; llama.cpp llama_sampler_typical)
    logits = jnp.array([[2.0] + [0.0] * 99])
    sp = mk_sp(1, temperature=1.0, top_k=0, top_p=1.0, min_p=0.0,
               typical_p=0.5, repeat_penalty=1.0)
    counts = jnp.zeros((1, 100), jnp.int32)
    seen = {int(sampling.sample(logits, counts, sp, jax.random.key(i))[0])
            for i in range(60)}
    assert 0 not in seen and len(seen) > 1


def test_typical_p_off_is_identity():
    logits = jnp.array([[3.0, 2.0, 1.0, 0.0]])
    counts = jnp.zeros((1, 4), jnp.int32)
    base = mk_sp(1, temperature=1.0, repeat_penalty=1.0)
    typ = mk_sp(1, temperature=1.0, repeat_penalty=1.0, typical_p=1.0)
    for i in range(10):
        t1 = sampling.sample(logits, counts, base, jax.random.key(i))
        t2 = sampling.sample(logits, counts, typ, jax.random.key(i))
        assert int(t1[0]) == int(t2[0])


def test_mirostat_v2_truncates_by_surprise_budget():
    # mu near zero admits only the top candidate (surprise of everything
    # else exceeds the budget) even though the static filters are wide open
    logits = jnp.array([[3.0, 2.5, 2.0, 1.0, 0.0]])
    counts = jnp.zeros((1, 5), jnp.int32)
    sp = mk_sp(1, temperature=1.0, top_k=0, top_p=1.0, repeat_penalty=1.0,
               mirostat=2, mirostat_tau=5.0, mirostat_eta=0.1)
    mu = jnp.array([0.05], jnp.float32)
    for i in range(20):
        tok, _ = sampling.sample(logits, counts, sp, jax.random.key(i), mu)
        assert int(tok[0]) == 0


def test_mirostat_mu_moves_toward_tau():
    # observed surprise far below tau → mu must RISE by eta*(tau - s)
    logits = jnp.array([[10.0, 0.0, 0.0, 0.0]])
    counts = jnp.zeros((1, 4), jnp.int32)
    tau, eta = 5.0, 0.5
    sp = mk_sp(1, temperature=1.0, top_k=0, top_p=1.0, repeat_penalty=1.0,
               mirostat=2, mirostat_tau=tau, mirostat_eta=eta)
    mu = jnp.array([2 * tau], jnp.float32)
    _, mu2 = sampling.sample(logits, counts, sp, jax.random.key(0), mu)
    assert float(mu2[0]) > float(mu[0]) - 1e-6  # s≈0 → mu += eta*tau
    np.testing.assert_allclose(float(mu2[0]), 2 * tau + eta * tau, atol=0.2)


def test_mirostat_off_slots_keep_mu_frozen():
    logits = jnp.tile(jnp.array([[1.0, 0.5, 0.0]]), (2, 1))
    counts = jnp.zeros((2, 3), jnp.int32)
    sp = sampling.SamplingParams.make(2, temperature=1.0,
                                      repeat_penalty=1.0)
    sp = dataclasses.replace(sp, mirostat=jnp.array([0, 2], jnp.int32))
    mu = jnp.array([7.7, 10.0], jnp.float32)
    keys = jnp.stack([jax.random.key(1), jax.random.key(2)])
    _, mu2 = sampling.sample(logits, counts, sp, keys, mu)
    assert float(mu2[0]) == np.float32(7.7)  # mirostat off → untouched
    assert float(mu2[1]) != 10.0         # mirostat on → updated


def test_mirostat_v1_zipf_cut_keeps_head():
    # steep zipf-ish distribution with a tiny mu: the derived k cut must
    # restrict sampling to the head of the distribution
    V = 64
    logits = (-1.5 * jnp.log(jnp.arange(1, V + 1, dtype=jnp.float32)))[None]
    counts = jnp.zeros((1, V), jnp.int32)
    sp = mk_sp(1, temperature=1.0, top_k=0, top_p=1.0, repeat_penalty=1.0,
               mirostat=1, mirostat_tau=2.0, mirostat_eta=0.1)
    mu = jnp.array([1.0], jnp.float32)
    seen = set()
    for i in range(40):
        tok, _ = sampling.sample(logits, counts, sp, jax.random.key(i), mu)
        seen.add(int(tok[0]))
    assert max(seen) < 8  # k ≈ (eps·2^mu / (1-V^-eps))^(1/s) is small


def test_typical_p_zero_keeps_most_typical_token():
    # a zero budget must NOT blank the distribution — min_keep=1 keeps
    # exactly the most-typical candidate (llama.cpp's limit behaviour),
    # deterministically. Here the p≈0.97 head is also the most typical
    # (its surprise is nearest the low entropy).
    logits = jnp.array([[5.0, 1.0, 0.0, -1.0]])
    counts = jnp.zeros((1, 4), jnp.int32)
    sp = mk_sp(1, temperature=1.0, top_k=0, top_p=1.0, repeat_penalty=1.0,
               typical_p=0.0)
    for i in range(15):
        assert int(sampling.sample(logits, counts, sp,
                                   jax.random.key(i))[0]) == 0


def test_typical_p_kept_set_is_temperature_invariant():
    # llama.cpp evaluates typ_p at T=1 (chain: top_k → typ_p → … → temp):
    # the same logits with different temperatures must keep the same set
    logits = jnp.array([[2.0] + [0.0] * 99])
    counts = jnp.zeros((1, 100), jnp.int32)
    for temp in (0.3, 1.0, 2.5):
        sp = mk_sp(1, temperature=temp, top_k=0, top_p=1.0,
                   repeat_penalty=1.0, typical_p=0.5)
        seen = {int(sampling.sample(logits, counts, sp,
                                    jax.random.key(i))[0])
                for i in range(40)}
        assert 0 not in seen   # the atypical head stays dropped at any T


def test_min_p_anchors_to_surviving_max_after_typical_drop():
    # typical_p drops the global argmax; min_p must then anchor to the
    # max SURVIVING probability, culling the low-prob tail (the
    # column-0 anchor would read ~0 and keep everything)
    logits = jnp.array([[2.0] + [0.0] * 30 + [-1.2] * 30])
    counts = jnp.zeros((1, 61), jnp.int32)
    sp = mk_sp(1, temperature=1.0, top_k=0, top_p=1.0, repeat_penalty=1.0,
               typical_p=0.838, min_p=0.4)
    seen = {int(sampling.sample(logits, counts, sp, jax.random.key(i))[0])
            for i in range(80)}
    assert 0 not in seen                      # typical dropped the head
    assert all(tok <= 30 for tok in seen)     # min_p culled the tail


def test_merge_options_clamps_invalid_mirostat():
    from ollama_operator_tpu.runtime.service import merge_options
    so, _, _ = merge_options({}, {"mirostat": 3})
    assert so.mirostat == 0        # llama.cpp: non-1/2 reads as off
    so, _, _ = merge_options({}, {"mirostat": 2, "mirostat_tau": 3.0})
    assert so.mirostat == 2 and so.mirostat_tau == 3.0
