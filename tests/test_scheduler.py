"""Scheduler: continuous batching under contention, cancellation, stats."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.scheduler import Scheduler

GREEDY = SlotOptions(temperature=0.0, repeat_penalty=1.0)


def make_stack(slots=2, **sched_kw):
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    eng = Engine(cfg, params,
                 ecfg=EngineConfig(max_slots=slots, max_seq_len=64,
                                   cache_dtype=jnp.float32,
                                   min_prefill_bucket=16))
    return cfg, params, eng, Scheduler(eng, **sched_kw)


def test_more_requests_than_slots_all_complete():
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        reqs = [sched.submit(np.array([i + 1, i + 2], np.int32), GREEDY,
                             max_tokens=5) for i in range(6)]
        outs = [list(r.tokens()) for r in reqs]
        assert all(len(o) == 5 for o in outs)
        # same prompt → same greedy tokens regardless of scheduling order
        r_again = sched.submit(np.array([1, 2], np.int32), GREEDY,
                               max_tokens=5)
        assert list(r_again.tokens()) == outs[0]
        assert sched.total_generated >= 30
    finally:
        sched.shutdown()


def test_concurrent_submitters():
    cfg, params, eng, sched = make_stack(slots=4)
    results = {}
    try:
        def worker(i):
            r = sched.submit(np.array([i + 1], np.int32), GREEDY,
                             max_tokens=4)
            results[i] = list(r.tokens())

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 8
        assert all(len(v) == 4 for v in results.values())
    finally:
        sched.shutdown()


def test_cancellation_frees_slot():
    cfg, params, eng, sched = make_stack(slots=1)
    try:
        r1 = sched.submit(np.array([1, 2], np.int32), GREEDY,
                          max_tokens=10_000)
        it = r1.tokens()
        next(it)  # running
        r1.cancel()
        rest = list(it)  # drains to done
        # slot must free up for the next request
        r2 = sched.submit(np.array([3], np.int32), GREEDY, max_tokens=3)
        assert len(list(r2.tokens())) == 3
    finally:
        sched.shutdown()


def test_stats_populated():
    cfg, params, eng, sched = make_stack(slots=1)
    try:
        r = sched.submit(np.array([5, 6, 7], np.int32), GREEDY, max_tokens=6)
        list(r.tokens())
        st = r.stats
        assert st.n_prompt == 3
        assert st.n_generated == 6
        assert st.ttft_s >= 0
        assert st.t_done >= st.t_first_token
    finally:
        sched.shutdown()


def test_oversized_prompt_rejected():
    cfg, params, eng, sched = make_stack(slots=1)
    try:
        try:
            sched.submit(np.zeros(64, np.int32), GREEDY, max_tokens=1)
            assert False
        except ValueError:
            pass
    finally:
        sched.shutdown()


def test_engine_failure_fails_requests_not_thread():
    """A decode exception must surface to callers, not kill the loop."""
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        calls = {"n": 0}
        real_decode_n = eng.decode_n
        real_launch = eng.decode_n_launch

        def flaky_decode_n(n=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected XLA error")
            return real_decode_n(n)

        def flaky_launch(n=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected XLA error")
            return real_launch(n)

        # a dead device step dies on BOTH entry points: the sync path and
        # the async double-buffered launch the scheduler uses by default
        eng.decode_n = flaky_decode_n
        eng.decode_n_launch = flaky_launch
        r1 = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=4)
        try:
            toks = list(r1.tokens())
            # token stream may complete if the error hit after its tokens
            assert len(toks) <= 4
        except RuntimeError as e:
            assert "injected" in str(e)
        assert sched._thread.is_alive()
        assert not sched.broken
        # the loop recovered: a fresh request completes normally
        r2 = sched.submit(np.array([3, 4], np.int32), GREEDY, max_tokens=3)
        assert len(list(r2.tokens())) == 3
    finally:
        sched.shutdown()


def test_repeated_engine_failures_mark_broken(monkeypatch):
    """Terminal `broken` is reached only after max_restarts supervised
    restarts ALSO fail — and then new submissions are refused."""
    # replay off: this test pins the pre-replay exactly-once error path
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    cfg, params, eng, sched = make_stack(slots=1, max_restarts=2,
                                         restart_backoff=0.001)
    try:
        def always_fail(n=None):
            raise RuntimeError("dead engine")

        eng.decode_n = always_fail
        eng.decode_n_launch = always_fail
        import pytest
        from ollama_operator_tpu.runtime.scheduler import SchedulerBroken
        for _ in range(3):
            r = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=4)
            with pytest.raises(RuntimeError):
                list(r.tokens())
        deadline = time.monotonic() + 5
        while not sched.broken and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sched.broken
        assert sched.n_restarts == 2   # two rebuilds tried before giving up
        with pytest.raises(SchedulerBroken):
            sched.submit(np.array([1], np.int32), GREEDY, max_tokens=1)
        # shutdown after broken must not hang on the already-returned loop
        t0 = time.monotonic()
        sched.shutdown()
        assert time.monotonic() - t0 < 5.0
    finally:
        sched.shutdown()   # idempotent


def test_fail_running_releases_slots_and_errors_each_stream_once(monkeypatch):
    """_fail_running: every running slot is released and every stream
    sees exactly ONE error item — then the freed slots serve new work."""
    # replay off: this test pins the fail-safe exactly-once error path
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        calls = {"n": 0}
        real_decode_n = eng.decode_n
        real_launch = eng.decode_n_launch

        def flaky(n=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom step")
            return real_decode_n(n)

        def flaky_launch(n=None):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("boom step")
            return real_launch(n)

        eng.decode_n = flaky
        eng.decode_n_launch = flaky_launch
        reqs = [sched.submit(np.array([i + 1, i + 2], np.int32), GREEDY,
                             max_tokens=64) for i in range(2)]
        import queue as queue_mod
        for r in reqs:
            # consume the stream; the error arrives as a raise
            try:
                list(r.tokens())
            except RuntimeError as e:
                assert "boom step" in str(e)
            # exactly once: the queue holds nothing after the error item
            try:
                extra = r.out.get_nowait()
                assert False, f"stream got extra item {extra!r}"
            except queue_mod.Empty:
                pass
        deadline = time.monotonic() + 5
        while sched.n_active and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sched.n_active == 0          # every slot released
        assert not any(eng.active)          # engine agrees
        r2 = sched.submit(np.array([9], np.int32), GREEDY, max_tokens=3)
        assert len(list(r2.tokens())) == 3
    finally:
        sched.shutdown()


def test_cancel_queued_request_frees_queue_slot():
    """cancel() of a still-QUEUED request must free its queue capacity
    and terminate its stream with done:cancelled."""
    cfg, params, eng, sched = make_stack(slots=1)
    sched._admission.max_queue = 1
    import pytest
    from ollama_operator_tpu.runtime.scheduler import SchedulerBusy
    try:
        r0 = sched.submit(np.array([1, 2], np.int32), GREEDY,
                          max_tokens=10_000)
        it = r0.tokens()
        next(it)                      # r0 holds the only slot
        rq = sched.submit(np.array([3], np.int32), GREEDY, max_tokens=1)
        with pytest.raises(SchedulerBusy):
            sched.submit(np.array([4], np.int32), GREEDY, max_tokens=1)
        rq.cancel()
        assert list(rq.tokens()) == []     # done:cancelled, no tokens
        assert rq.done_reason == "cancelled"
        # its queue slot is free again while r0 still runs
        r2 = sched.submit(np.array([5], np.int32), GREEDY, max_tokens=1)
        r0.cancel()
        list(it)
        list(r2.tokens())
    finally:
        sched.shutdown()


def test_queue_full_raises_busy():
    cfg, params, eng, sched = make_stack(slots=1)
    sched._admission.max_queue = 2
    import pytest
    from ollama_operator_tpu.runtime.scheduler import SchedulerBusy
    try:
        # occupy the slot with a long request, then overfill the queue
        r0 = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=40)
        time.sleep(0.2)  # let it get admitted
        held = [sched.submit(np.array([3], np.int32), GREEDY, max_tokens=1)
                for _ in range(2)]
        with pytest.raises(SchedulerBusy):
            sched.submit(np.array([4], np.int32), GREEDY, max_tokens=1)
        r0.cancel()
        for r in held:
            list(r.tokens())
    finally:
        sched.shutdown()
