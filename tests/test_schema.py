"""Schema-constrained decoding: the skeleton machine (ops/schema.py).

Upstream ollama enforces `format: {…schema}` via llama.cpp's GBNF
compiler; round 1 silently downgraded schemas to generic JSON. These
tests pin the machine's byte-level semantics (incl. token pieces that
cross literal/hole boundaries), mask exactness against brute force, and
end-to-end conformance through the real scheduler.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib, decoder
from ollama_operator_tpu.ops import schema as S
from ollama_operator_tpu.ops.constrain import TokenTable
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.scheduler import Scheduler

PERSON = {"type": "object",
          "properties": {"name": {"type": "string"},
                         "age": {"type": "integer"},
                         "tags": {"type": "array",
                                  "items": {"type": "string"}},
                         "ok": {"type": "boolean"}}}


def accepts(sch, text: bytes) -> bool:
    st = S.machine_init(sch.root)
    for b in text:
        st = S.machine_advance(sch.root, st, b)
        if st is None:
            return False
    return S.machine_eos_ok(st)


def test_machine_accepts_conforming():
    sch = S.compile_schema(PERSON)
    assert sch is not None
    good = b'{"name":"bo","age":42,"tags":["x","y"],"ok":true}'
    assert accepts(sch, good)
    assert accepts(sch, b'{"name":"","age":-7,"tags":[],"ok":false}')


@pytest.mark.parametrize("bad", [
    b'{"name":"bo"}',                                  # missing keys
    b'{"age":42,"name":"bo","tags":[],"ok":true}',     # wrong order
    b'{"name":7,"age":42,"tags":[],"ok":true}',        # wrong type
    b'{"name":"bo","age":4.5,"tags":[],"ok":true}',    # float for integer
    b'{"name":"bo","age":42,"tags":[1],"ok":true}',    # wrong item type
    b'{"name":"bo","age":42,"tags":[],"ok":true,"z":1}',  # extra key
    b'{"name":"bo","age":42,"tags":[],"ok":null}',     # null for boolean
    b'["x"]',                                          # not an object
])
def test_machine_rejects_nonconforming(bad):
    sch = S.compile_schema(PERSON)
    assert not accepts(sch, bad)


def test_machine_enum_and_nested():
    sch = S.compile_schema({
        "type": "object",
        "properties": {
            "color": {"enum": ["red", "green"]},
            "point": {"type": "object",
                      "properties": {"x": {"type": "number"},
                                     "y": {"type": "number"}}},
        }})
    assert accepts(sch, b'{"color":"red","point":{"x":1.5,"y":-2e3}}')
    assert not accepts(sch, b'{"color":"blue","point":{"x":1,"y":2}}')
    assert not accepts(sch, b'{"color":"red","point":{"x":1}}')
    # enum prefix ambiguity
    sch2 = S.compile_schema({"enum": ["a", "ab"]})
    assert accepts(sch2, b'"a"')
    assert accepts(sch2, b'"ab"')
    assert not accepts(sch2, b'"abc"')


def test_unsupported_schemas_return_none():
    for bad in ({"anyOf": []},                       # empty union
                {"not": {"type": "string"}},
                {"type": "object", "properties": {"a": {"type": "string"}},
                 "required": []},
                {"type": "object", "properties": {},
                 "additionalProperties": True},
                {"type": "string", "pattern": "^a"},
                {"type": ["string", "null"]}):
        assert S.compile_schema(bad) is None, bad


def test_mask_matches_brute_force():
    """The first-byte-indexed mask fill must equal the definition: token
    allowed iff every byte advances."""
    pieces = [b"", b'{"', b'{"name"', b'name', b'":"', b'ab', b'"',
              b'","age":', b'12', b'3', b',"tags":["', b'"],"ok":tr',
              b'ue}', b'x', b'{', b'}', b'[', b']', b'true', b'-', b'.5']
    table = TokenTable(pieces, eog_ids=[0])
    sch = S.compile_schema(PERSON)
    st = S.machine_init(sch.root)
    # walk a few states deep, checking the mask at each
    for step_bytes in (b"", b'{"name":"a', b'{"name":"ab","age":1'):
        st = S.machine_init(sch.root)
        for b in step_bytes:
            st = S.machine_advance(sch.root, st, b)
            assert st is not None
        mask = sch.mask_for(table, st)
        for tid, piece in enumerate(pieces):
            want = False
            if piece:
                s2 = st
                for b in piece:
                    s2 = S.machine_advance(sch.root, s2, b)
                    if s2 is None:
                        break
                want = s2 is not None
            got = bool(mask[tid >> 5] & np.uint32(1 << (tid & 31)))
            assert got == want, (step_bytes, tid, piece)


def test_scheduler_schema_constrained_output_conforms():
    """End to end on the tiny model through the real scheduler: sampled
    output must parse AND conform to the schema, at several seeds.

    The token table is byte-complete (every printable byte has a
    single-byte token), so token-level masks can never paint the sampler
    into an inexpressible state — the same property real BPE vocabs have
    via byte fallback tokens."""
    from ollama_operator_tpu.ops.schema import SchemaConstraint

    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    eng = Engine(cfg, params,
                 ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                   cache_dtype=jnp.float32,
                                   min_prefill_bucket=16))
    sched = Scheduler(eng)
    pieces = ([b""] + [bytes([c]) for c in range(32, 127)]
              + [b'{"', b'":', b'","', b'"}', b"true", b"false", b"12"])
    pieces = (pieces + [b""] * (cfg.vocab_size - 1 - len(pieces)))[
        : cfg.vocab_size - 1] + [b"</s>"]
    EOS = cfg.vocab_size - 1
    table = TokenTable(pieces, eog_ids=[EOS])
    schema = {"type": "object",
              "properties": {"a": {"type": "integer"},
                             "b": {"enum": ["x", "y"]}}}
    sch = S.compile_schema(schema)
    try:
        conforming = 0
        for seed in range(3):
            c = SchemaConstraint(sch, table)
            req = sched.submit(
                [5, 9, 2], SlotOptions(temperature=0.9, seed=seed,
                                       repeat_penalty=1.0),
                max_tokens=120, eog_ids=frozenset([EOS]), constraint=c)
            toks = list(req.tokens())
            data = b"".join(table.pieces[t] for t in toks)
            assert accepts(sch, data) or req.stats.n_generated >= 120, data
            if req.stats.n_generated < 120:
                obj = json.loads(data.decode())
                assert isinstance(obj.get("a"), int)
                assert obj.get("b") in ("x", "y")
                conforming += 1
        assert conforming >= 1
    finally:
        sched.shutdown()


def test_whitelist_rejects_unimplemented_keywords():
    """Keywords outside the implemented subset must fall back (whitelist
    semantics): compiling past exclusiveMinimum/multipleOf/... would
    silently under-constrain."""
    for bad in ({"type": "number", "minimum": 0},     # float ranges
                {"type": "integer", "minimum": 1.5},  # non-int bound
                {"type": "number", "multipleOf": 2},
                {"type": "array", "items": {"type": "string"},
                 "uniqueItems": True},
                {"type": "object", "properties": {"a": {"type": "string"}},
                 "minProperties": 1},
                {"type": "string", "contentEncoding": "base64"},
                {"anyOf": [{"type": "string"}], "minLength": 1}):
        assert S.compile_schema(bad) is None, bad
    # annotation-only keywords stay supported
    ok = S.compile_schema({"type": "string", "title": "name",
                           "description": "d", "default": "x"})
    assert ok is not None


def test_anyof_alternation():
    """anyOf compiles to NFA branches that prune as bytes disambiguate
    (round-2 VERDICT weak #7: the whitelist used to reject it)."""
    sch = S.compile_schema({"anyOf": [
        {"type": "object", "properties": {"a": {"type": "integer"}}},
        {"type": "object", "properties": {"b": {"type": "string"}}},
        {"type": "string"},
    ]})
    assert sch is not None
    assert accepts(sch, b'{"a":42}')
    assert accepts(sch, b'{"b":"hi"}')
    assert accepts(sch, b'"plain"')
    assert not accepts(sch, b'{"a":"nope"}')   # a must be integer
    assert not accepts(sch, b'{"c":1}')
    assert not accepts(sch, b'7')              # number not in the union
    # nested anyOf inside a property
    sch2 = S.compile_schema({"type": "object", "properties": {
        "v": {"anyOf": [{"type": "boolean"}, {"type": "null"}]}}})
    assert accepts(sch2, b'{"v":true}')
    assert accepts(sch2, b'{"v":null}')
    assert not accepts(sch2, b'{"v":1}')
    # oneOf constrains as the anyOf union (documented over-approximation)
    assert S.compile_schema({"oneOf": [{"type": "string"},
                                       {"type": "null"}]}) is not None


def test_integer_range_digit_dfa():
    """minimum/maximum on integers: prefixes are allowed iff SOME digit
    completion lands in range; out-of-range completions are never
    emittable (round-2 VERDICT weak #7: numeric ranges fell back)."""
    sch = S.compile_schema({"type": "integer", "minimum": 5,
                            "maximum": 120})
    assert sch is not None
    for good in (b"5", b"9", b"42", b"120", b"100"):
        assert accepts(sch, good), good
    for bad in (b"4", b"121", b"130", b"1000", b"-3", b"05", b"4.5"):
        assert not accepts(sch, bad), bad
    # prefix viability: "1" must be allowed (→ 10..120), "13" must not
    # be COMPLETABLE to something in range beyond 13 itself? 13 is in
    # range; "13" accepts. But "121" dies at its final byte:
    st = S.machine_init(sch.root)
    for b in b"12":
        st = S.machine_advance(sch.root, st, b)
        assert st is not None
    assert S.machine_advance(sch.root, st, ord("1")) is None

    neg = S.compile_schema({"type": "integer", "minimum": -30,
                            "maximum": -10})
    for good in (b"-30", b"-10", b"-22"):
        assert accepts(neg, good), good
    for bad in (b"-31", b"-9", b"-5", b"0", b"7", b"-100"):
        assert not accepts(neg, bad), bad

    # exclusive bounds tighten by one
    excl = S.compile_schema({"type": "integer", "exclusiveMinimum": 0,
                             "exclusiveMaximum": 10})
    assert accepts(excl, b"1") and accepts(excl, b"9")
    assert not accepts(excl, b"0") and not accepts(excl, b"10")

    # single-sided bound
    pos = S.compile_schema({"type": "integer", "minimum": 0})
    assert accepts(pos, b"0") and accepts(pos, b"12345678901234")
    assert not accepts(pos, b"-1")

    # unsatisfiable range falls back rather than constraining to nothing
    assert S.compile_schema({"type": "integer", "minimum": 5,
                             "maximum": 4}) is None

    # in an object property, the delimiter closes the integer lazily
    obj = S.compile_schema({"type": "object", "properties": {
        "n": {"type": "integer", "minimum": 1, "maximum": 12}}})
    assert accepts(obj, b'{"n":12}')
    assert not accepts(obj, b'{"n":13}')
    assert not accepts(obj, b'{"n":0}')


def test_anyof_mask_matches_brute_force():
    """Mask exactness holds for the NFA (anyOf + bounded-integer) states
    exactly as for the deterministic skeleton."""
    pieces = [b"", b'{"a":', b'{"b":', b'"', b'x', b'1', b'12', b'9',
              b'}', b'"}', b'true', b'-', b'0', b'5}']
    table = TokenTable(pieces, eog_ids=[0])
    sch = S.compile_schema({"anyOf": [
        {"type": "object",
         "properties": {"a": {"type": "integer", "minimum": 3,
                              "maximum": 15}}},
        {"type": "object", "properties": {"b": {"type": "string"}}},
    ]})
    for step_bytes in (b"", b'{"', b'{"a":1', b'{"b":"x'):
        st = S.machine_init(sch.root)
        alive = True
        for b in step_bytes:
            st = S.machine_advance(sch.root, st, b)
            if st is None:
                alive = False
                break
        assert alive, step_bytes
        mask = sch.mask_for(table, st)
        for tid, piece in enumerate(pieces):
            want = False
            if piece:
                s2 = st
                for b in piece:
                    s2 = S.machine_advance(sch.root, s2, b)
                    if s2 is None:
                        break
                want = s2 is not None
            got = bool(mask[tid >> 5] & np.uint32(1 << (tid & 31)))
            assert got == want, (step_bytes, tid, piece)


def test_any_hole_nesting_reuses_abstract_mask_states():
    """Deep '[[[…' inside an "any" hole must NOT mint a fresh mask per
    depth — leaf states cache by the PDA abstract stack-suffix key."""
    pieces = [b""] + [bytes([c]) for c in range(32, 127)]
    table = TokenTable(pieces, eog_ids=[0])
    sch = S.compile_schema({"type": "object",
                            "properties": {"v": {}}})
    st = S.machine_init(sch.root)
    for b in b'{"v":':
        st = S.machine_advance(sch.root, st, b)
    depth_keys = set()
    for _ in range(table.max_len + 8):
        st = S.machine_advance(sch.root, st, ord("["))
        sch.mask_for(table, st)
        depth_keys.add(sch._state_key(table, st))
    # beyond max_len depth the abstract key saturates
    assert len(depth_keys) <= table.max_len + 1
    assert len(sch._masks) <= table.max_len + 4


def test_native_schema_fill_parity_and_speed():
    """native/grammar.cpp's schema_fill_mask must agree bit-for-bit with
    the Python NFA sweep on every state of a multi-construct walk, and
    retire the cold hole-state fill cost (round-2 VERDICT weak #7 /
    next-6: the Python sweep was seconds for 100k vocabs)."""
    import time

    from ollama_operator_tpu.ops.constrain import _load_native
    if _load_native() is None:
        pytest.skip("native grammar lib unavailable (no g++?)")

    rng = np.random.default_rng(3)
    pieces = [b""] + [bytes(rng.integers(32, 127, size=int(n)))
                      for n in rng.integers(1, 6, size=4096)]
    pieces += [b'{"', b'":', b'",', b'"}', b'12', b'-3', b'true', b'[',
               b']', b'a', b'5', b'}', b'{']
    table = TokenTable(pieces, eog_ids=[0])

    sch = S.compile_schema({"anyOf": [
        {"type": "object",
         "properties": {"name": {"type": "string"},
                        "n": {"type": "integer", "minimum": -30,
                              "maximum": 1200},
                        "tags": {"type": "array",
                                 "items": {"enum": ["a", "bb", 3]}},
                        "v": {}}},
        {"type": "string"},
    ]})
    assert sch is not None and sch._prog is not None

    walk = b'{"name":"ab","n":-2,"tags":["bb",3],"v":[{"x":1},'
    st = S.machine_init(sch.root)
    checked = 0
    t_native = t_python = 0.0
    for i in range(len(walk) + 1):
        # parity at every prefix state (incl. hole interiors + NFA splits)
        t0 = time.perf_counter()
        native = sch._native_fill(table, st)
        t_native += time.perf_counter() - t0
        assert native is not None, f"native bailed at prefix {walk[:i]!r}"
        t0 = time.perf_counter()
        ref = np.zeros(table.n_words, np.uint32)
        for tid, piece in enumerate(table.pieces):
            if not piece:
                continue
            s2 = st
            for b in piece:
                s2 = S.machine_advance(sch.root, s2, b)
                if s2 is None:
                    break
            if s2 is not None:
                ref[tid >> 5] |= np.uint32(1 << (tid & 31))
        t_python += time.perf_counter() - t0
        assert (native == ref).all(), (i, walk[:i])
        checked += 1
        if i < len(walk):
            st = S.machine_advance(sch.root, st, walk[i])
            assert st is not None, walk[: i + 1]
    assert checked == len(walk) + 1
    print(f"\nnative schema fill: {checked} states x {len(pieces)} tokens; "
          f"python {t_python:.3f}s vs native {t_native:.3f}s "
          f"({t_python / max(t_native, 1e-9):.0f}x)")
    # the point of the port: the cold sweep must be far cheaper
    assert t_native * 3 < t_python
