"""End-to-end HTTP API tests: pull from a fake registry, then exercise the
Ollama surface over real sockets with a tiny model on the CPU backend.

This is tier (c) of the test pyramid (SURVEY.md §4): the same contract the
reference's probes and clients depend on (/api/tags probe at pod.go:44,
generate/chat/OpenAI from the getting-started docs)."""

import json
import os
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import EngineConfig
from ollama_operator_tpu.server.app import ModelManager, serve

from fake_registry import FakeRegistry
from test_transcode import write_tiny_llama_gguf


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("server")
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    gguf_path = str(tmp / "tiny.gguf")
    write_tiny_llama_gguf(gguf_path, cfg, params)
    with open(gguf_path, "rb") as f:
        gguf_bytes = f.read()

    reg = FakeRegistry()
    url = reg.start()
    reg.add_model("library", "tiny", "latest", gguf_bytes,
                  template="{{ .System }}|{{ .Prompt }}",
                  params={"temperature": 0.0, "repeat_penalty": 1.0,
                          "num_predict": 8})

    manager = ModelManager(str(tmp / "store"), cache_dir=str(tmp / "cache"),
                           ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                             cache_dtype=jnp.float32,
                                             min_prefill_bucket=16),
                           engine_dtype="float32")
    httpd = serve(manager, "127.0.0.1", 0)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    # pull once here so individually-selected tests don't depend on an
    # earlier test in file order having pulled
    post(base, "/api/pull",
         {"model": f"http://{url.split('://')[1]}/library/tiny:latest"},
         stream=True)
    yield {"base": base, "registry_url": url, "manager": manager,
           "registry": reg, "gguf_path": gguf_path}
    httpd.shutdown()
    reg.stop()


def post(base, path, payload, stream=False):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    resp = urllib.request.urlopen(req, timeout=120)
    if stream:
        lines = [json.loads(l) for l in resp.read().decode().splitlines()
                 if l.strip() and not l.startswith("data:")]
        return lines
    return json.loads(resp.read())


def get(base, path):
    return urllib.request.urlopen(base + path, timeout=30).read().decode()


def test_root_banner(stack):
    assert get(stack["base"], "/") == "Ollama is running"
    assert "version" in json.loads(get(stack["base"], "/api/version"))


def test_pull_streams_progress(stack):
    model_ref = f"{stack['registry_url']}/library/tiny:latest"
    lines = post(stack["base"], "/api/pull", {"model": model_ref},
                 stream=True)
    statuses = [l.get("status", "") for l in lines]
    assert statuses[0] == "pulling manifest"
    assert statuses[-1] == "success"
    assert any(l.get("total") for l in lines)


def test_tags_lists_pulled_model(stack):
    tags = json.loads(get(stack["base"], "/api/tags"))
    names = [m["name"] for m in tags["models"]]
    assert any("tiny" in n for n in names)
    m = tags["models"][0]
    assert m["details"]["format"] == "gguf"
    assert m["details"]["family"] == "llama"


def _model_name(stack):
    host = stack["registry_url"].split("://")[1]
    return f"http://{host}/library/tiny:latest"


def test_generate_stream(stack):
    lines = post(stack["base"], "/api/generate",
                 {"model": _model_name(stack), "prompt": "t1 t2",
                  "options": {"num_predict": 5}}, stream=True)
    assert lines[-1]["done"] is True
    assert lines[-1]["eval_count"] >= 1
    assert lines[-1]["prompt_eval_count"] >= 2
    text = "".join(l.get("response", "") for l in lines)
    assert text  # deterministic tiny model emits something
    assert "context" in lines[-1]


def test_generate_nonstream_deterministic(stack):
    payload = {"model": _model_name(stack), "prompt": "t1 t2",
               "stream": False, "options": {"num_predict": 6}}
    r1 = post(stack["base"], "/api/generate", payload)
    r2 = post(stack["base"], "/api/generate", payload)
    assert r1["response"] == r2["response"]  # temperature 0 from params layer
    assert r1["done_reason"] in ("stop", "length")


def test_generate_with_context_continuation(stack):
    r1 = post(stack["base"], "/api/generate",
              {"model": _model_name(stack), "prompt": "t1",
               "stream": False, "options": {"num_predict": 3}})
    r2 = post(stack["base"], "/api/generate",
              {"model": _model_name(stack), "prompt": "t2",
               "context": r1["context"], "stream": False,
               "options": {"num_predict": 3}})
    assert r2["prompt_eval_count"] > r1["prompt_eval_count"]


def test_template_applied(stack):
    # template is "{{ .System }}|{{ .Prompt }}"; raw=true must bypass it
    r_t = post(stack["base"], "/api/generate",
               {"model": _model_name(stack), "prompt": "t3",
                "system": "t9", "stream": False,
                "options": {"num_predict": 2}})
    r_raw = post(stack["base"], "/api/generate",
                 {"model": _model_name(stack), "prompt": "t3", "raw": True,
                  "stream": False, "options": {"num_predict": 2}})
    assert r_t["prompt_eval_count"] != r_raw["prompt_eval_count"]


def test_chat_endpoint(stack):
    r = post(stack["base"], "/api/chat",
             {"model": _model_name(stack),
              "messages": [{"role": "user", "content": "t4 t5"}],
              "stream": False, "options": {"num_predict": 4}})
    assert r["message"]["role"] == "assistant"
    assert r["done"] is True


def test_openai_chat_completions(stack):
    r = post(stack["base"], "/v1/chat/completions",
             {"model": _model_name(stack),
              "messages": [{"role": "user", "content": "t1"}],
              "max_tokens": 4})
    assert r["object"] == "chat.completion"
    assert r["choices"][0]["message"]["role"] == "assistant"
    assert r["usage"]["completion_tokens"] >= 1


def test_show_and_ps(stack):
    r = post(stack["base"], "/api/show", {"model": _model_name(stack)})
    assert "FROM" in r["modelfile"]
    assert r["template"] == "{{ .System }}|{{ .Prompt }}"
    assert r["details"]["family"] == "llama"
    ps = json.loads(get(stack["base"], "/api/ps"))
    assert len(ps["models"]) == 1


def test_copy_and_delete(stack):
    post(stack["base"], "/api/copy",
         {"source": _model_name(stack), "destination": "tiny-copy"})
    tags = json.loads(get(stack["base"], "/api/tags"))
    assert any(m["name"] == "tiny-copy:latest" for m in tags["models"])
    req = urllib.request.Request(
        stack["base"] + "/api/delete",
        data=json.dumps({"model": "tiny-copy"}).encode(), method="DELETE")
    urllib.request.urlopen(req, timeout=30)
    tags = json.loads(get(stack["base"], "/api/tags"))
    assert not any(m["name"] == "tiny-copy:latest" for m in tags["models"])


def test_embeddings(stack):
    r = post(stack["base"], "/api/embeddings",
             {"model": _model_name(stack), "prompt": "t1 t2"})
    assert len(r["embedding"]) == 64  # tiny dim


def test_metrics_exposed(stack):
    text = get(stack["base"], "/metrics")
    assert "tpu_model_generated_tokens_total" in text
    assert "tpu_model_ttft_seconds_bucket" in text


def test_missing_model_404(stack):
    try:
        post(stack["base"], "/api/show", {"model": "doesnotexist"})
        assert False
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert "error" in json.loads(e.read())


def test_null_stop_option_tolerated(stack):
    r = post(stack["base"], "/api/generate",
             {"model": _model_name(stack), "prompt": "t1", "stream": False,
              "options": {"num_predict": 2, "stop": None}})
    assert r["done"] is True


def test_stop_sequences(stack):
    r = post(stack["base"], "/api/generate",
             {"model": _model_name(stack), "prompt": "t1", "stream": False,
              "raw": True,
              "options": {"num_predict": 10, "stop": ["t"],
                          "temperature": 0.0}})
    assert "t" not in r["response"]
    assert r["done_reason"] == "stop"


def test_pull_progress_carries_digest(stack):
    model_ref = f"{stack['registry_url']}/library/tiny:latest"
    lines = post(stack["base"], "/api/pull", {"model": model_ref},
                 stream=True)
    with_digest = [l for l in lines if l.get("digest")]
    assert with_digest, "blob progress events must carry the layer digest"
    assert all(l["digest"].startswith("sha256:") for l in with_digest)


def test_create_inherits_base_layers(stack):
    """FROM <local model> keeps the base template/params (ollama semantics)."""
    base_name = _model_name(stack)
    post(stack["base"], "/api/create",
         {"name": "derived", "stream": False,
          "modelfile": f"FROM {base_name}\nSYSTEM \"be terse\""})
    show = post(stack["base"], "/api/show", {"name": "derived"})
    # template inherited from the base model, system overridden
    assert show["template"] == "{{ .System }}|{{ .Prompt }}"
    assert show["system"] == "be terse"
    assert "temperature" in show["parameters"]
    # params merge: new PARAMETER wins, base keys survive
    post(stack["base"], "/api/create",
         {"name": "derived2", "stream": False,
          "modelfile": f"FROM {base_name}\nPARAMETER temperature 0.5"})
    show2 = post(stack["base"], "/api/show", {"name": "derived2"})
    assert "0.5" in show2["parameters"]
    assert "num_predict" in show2["parameters"]


def test_readyz(stack):
    assert get(stack["base"], "/readyz") == "ok"


def test_streaming_backpressure_is_http_503(stack):
    """Scheduler admission must happen BEFORE chunked headers: a full queue
    on a stream=true request has to surface as a real HTTP 503 (what load
    balancers key on), not an error chunk inside a 200 stream."""
    from ollama_operator_tpu.runtime.scheduler import SchedulerBusy

    lm = stack["manager"].require_loaded(_model_name(stack))
    orig = lm.scheduler.submit

    def full_submit(*a, **k):
        raise SchedulerBusy("queue full")

    lm.scheduler.submit = full_submit
    try:
        req = urllib.request.Request(
            stack["base"] + "/api/generate",
            data=json.dumps({"model": _model_name(stack), "prompt": "x",
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
    finally:
        lm.scheduler.submit = orig


def test_client_disconnect_mid_stream_frees_slot(stack):
    """Dropping the socket mid-stream must cancel the request and free the
    decode slot promptly (the write failure closes the generator, whose
    cleanup cancels the scheduler request) — a slot burned to max_tokens
    after a disconnect is capacity stolen from live clients."""
    import socket
    import time as _time

    lm = stack["manager"].require_loaded(_model_name(stack))
    captured = {}
    orig = lm.scheduler.submit

    def capture_submit(*a, **k):
        captured["req"] = orig(*a, **k)
        return captured["req"]

    lm.scheduler.submit = capture_submit
    host, port = stack["base"].split("://")[1].split(":")
    body = json.dumps({"model": _model_name(stack), "prompt": "t1",
                       "stream": True, "raw": True,
                       "options": {"num_predict": 10_000,
                                   "temperature": 0.0,
                                   "stream_flush_tokens": 1}}).encode()
    s = socket.create_connection((host, int(port)), timeout=60)
    try:
        s.sendall(b"POST /api/generate HTTP/1.1\r\n"
                  b"Host: " + host.encode() + b"\r\n"
                  b"Content-Type: application/json\r\n"
                  b"Content-Length: " + str(len(body)).encode() +
                  b"\r\n\r\n" + body)
        buf = b""
        while b'"done": false' not in buf:
            chunk = s.recv(4096)
            assert chunk, "stream closed before first frame"
            buf += chunk
    finally:
        # abrupt close mid-stream, without reading the rest
        s.close()
        lm.scheduler.submit = orig
    req = captured["req"]
    deadline = _time.time() + 60
    while _time.time() < deadline and lm.scheduler.n_active:
        _time.sleep(0.02)
    assert lm.scheduler.n_active == 0
    # cancelled well before max_tokens, not decoded to completion
    assert req.stats.n_generated < req.max_tokens


def test_broken_scheduler_reloads_on_next_request(stack):
    """A wedged decode loop must not zombie the pod: load() tears down a
    broken scheduler and brings up a fresh engine for the same model."""
    mgr = stack["manager"]
    name = _model_name(stack)
    lm = mgr.require_loaded(name)
    lm.scheduler.broken = True
    with pytest.raises(urllib.error.HTTPError) as ei:
        get(stack["base"], "/readyz")
    assert ei.value.code == 503
    with pytest.raises(urllib.error.HTTPError) as ei:
        get(stack["base"], "/livez")
    assert ei.value.code == 503
    lm2 = mgr.require_loaded(name)
    assert lm2 is not lm
    assert not lm2.scheduler.broken
    # and it actually serves
    r = post(stack["base"], "/api/generate",
             {"model": name, "prompt": "t1", "stream": False,
              "options": {"num_predict": 2}})
    assert r["done"] is True


def test_drain_flips_readyz_and_sheds_submits(stack):
    """Graceful drain over HTTP: begin_drain() flips /readyz to 503
    "draining" while /livez stays ok (the kubelet must not restart a
    pod mid-drain), new generates shed 503 + Retry-After, and /api/ps
    reports the lifecycle state."""
    mgr = stack["manager"]
    name = _model_name(stack)
    lm = mgr.require_loaded(name)
    try:
        mgr.begin_drain()
        mgr.begin_drain()                      # idempotent
        with pytest.raises(urllib.error.HTTPError) as ei:
            get(stack["base"], "/readyz")
        assert ei.value.code == 503
        assert "draining" in ei.value.read().decode()
        assert get(stack["base"], "/livez") == "ok"
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(stack["base"], "/api/generate",
                 {"model": name, "prompt": "t1", "stream": False,
                  "options": {"num_predict": 2}})
        assert ei.value.code == 503
        assert int(ei.value.headers.get("Retry-After", "0")) >= 1
        ps = json.loads(get(stack["base"], "/api/ps"))
        assert ps["models"][0]["lifecycle"]["state"] == "draining"
        assert ps["models"][0]["lifecycle"]["replay"]["enabled"] is True
    finally:
        # the stack fixture is module-scoped: undo the (normally
        # terminal) drain so later tests see a serving pod
        mgr.draining = False
        lm.scheduler.draining = False
    assert get(stack["base"], "/readyz") == "ok"
    r = post(stack["base"], "/api/generate",
             {"model": name, "prompt": "t1", "stream": False,
              "options": {"num_predict": 2}})
    assert r["done"] is True


def test_v1_embeddings_endpoint(stack):
    out = post(stack["base"], "/v1/embeddings",
               {"model": _model_name(stack), "input": ["hello", "world"]})
    assert out["object"] == "list"
    assert len(out["data"]) == 2
    assert out["data"][0]["object"] == "embedding"
    assert len(out["data"][0]["embedding"]) > 0


def test_generate_format_json(stack):
    """format: "json" over the HTTP surface: pull a tiny model with a
    JSON-capable vocab, then every generate must emit a valid JSON prefix
    (a complete value whenever it stopped on EOS)."""
    import string

    import numpy as np

    from ollama_operator_tpu.ops.constrain import (INITIAL_STATE,
                                                   advance_bytes, eos_ok)
    from test_transcode import write_tiny_llama_gguf as write_gguf

    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(7),
                                 dtype=jnp.float32)
    pieces = ["<unk>", "<s>", "</s>"] + list('{}[]":,-. ') + \
        [str(d) for d in range(10)] + ["true", "false", "null"] + \
        list(string.ascii_lowercase)
    pieces += [f"x{i}" for i in range(cfg.vocab_size - len(pieces))]
    types = [3, 3, 3] + [1] * (cfg.vocab_size - 3)
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        p = td + "/tinyjson.gguf"
        write_gguf(p, cfg, params, tokens=pieces, token_types=types,
                   eos_id=2)
        with open(p, "rb") as f:
            blob = f.read()
    stack["registry"].add_model(
        "library", "tinyjson", "latest", blob,
        template="{{ .Prompt }}",
        params={"temperature": 0.9, "repeat_penalty": 1.0})
    ref = f"{stack['registry_url']}/library/tinyjson:latest"
    post(stack["base"], "/api/pull", {"model": ref}, stream=True)

    completed = 0
    for seed in range(3):
        r = post(stack["base"], "/api/generate",
                 {"model": ref, "prompt": "abc", "stream": False,
                  "format": "json",
                  "options": {"num_predict": 80, "seed": seed}})
        data = r["response"].encode()
        st = advance_bytes(INITIAL_STATE, data)
        assert st is not None, (seed, data)
        if r["done_reason"] == "stop":
            json.loads(r["response"])
            completed += 1
    assert completed >= 1


def test_keep_alive_zero_unloads(stack):
    """Empty prompt + keep_alive 0 is the `ollama stop` path; the model
    must leave /api/ps and reload on the next request."""
    name = _model_name(stack)
    post(stack["base"], "/api/pull", {"model": name}, stream=True)
    post(stack["base"], "/api/generate",
         {"model": name, "prompt": "t1", "stream": False,
          "options": {"num_predict": 2}})
    assert len(json.loads(get(stack["base"], "/api/ps"))["models"]) == 1
    r = post(stack["base"], "/api/generate",
             {"model": name, "prompt": "", "keep_alive": 0})
    assert r["done_reason"] == "unload"
    assert json.loads(get(stack["base"], "/api/ps"))["models"] == []
    # transparent reload
    r = post(stack["base"], "/api/generate",
             {"model": name, "prompt": "t1", "stream": False,
              "options": {"num_predict": 2}})
    assert r["done"] is True


def test_push_roundtrip(stack):
    """Push a locally-created model to the registry (docker v2 upload flow)
    and verify the registry accepted manifest + blobs."""
    name = _model_name(stack)
    post(stack["base"], "/api/pull", {"model": name}, stream=True)
    host = stack["registry_url"].split("://")[1]
    dst = f"http://{host}/library/tiny-pushed:latest"
    post(stack["base"], "/api/copy", {"source": name, "destination": dst})
    lines = post(stack["base"], "/api/push", {"model": dst}, stream=True)
    statuses = [l.get("status", "") for l in lines]
    assert statuses[-1] == "success", lines
    reg = stack["registry"]
    assert ("library", "tiny-pushed", "latest") in reg.manifests
    pushed = reg.manifests[("library", "tiny-pushed", "latest")]
    for layer in pushed["layers"] + [pushed["config"]]:
        assert layer["digest"] in reg.blobs

    # non-stream form and digest mismatch rejection are covered by the
    # fake registry's PUT validation: re-push hits the HEAD fast path
    r = post(stack["base"], "/api/push", {"model": dst, "stream": False})
    assert r["status"] == "success"


def test_chat_tools_surface(stack):
    """tools on a template without .Tools → 400; with a tools-aware
    template the request renders and answers (content or tool_calls)."""
    name = _model_name(stack)
    post(stack["base"], "/api/pull", {"model": name}, stream=True)
    weather = {"type": "function",
               "function": {"name": "get_weather",
                            "parameters": {"type": "object"}}}
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(stack["base"], "/api/chat",
             {"model": name, "stream": False,
              "messages": [{"role": "user", "content": "t1"}],
              "tools": [weather]})
    assert ei.value.code == 400

    tpl = ("{{ if .Tools }}{{ range .Tools }}{{ json .Function }}"
           "{{ end }}{{ end }}{{ range .Messages }}{{ .Content }}"
           "{{ end }}")
    post(stack["base"], "/api/create",
         {"model": "tiny-tools", "stream": False,
          "modelfile": f"FROM {name}\nTEMPLATE \"\"\"{tpl}\"\"\""})
    r = post(stack["base"], "/api/chat",
             {"model": "tiny-tools", "stream": False,
              "messages": [{"role": "user", "content": "t1"}],
              "tools": [weather], "options": {"num_predict": 4}})
    assert r["done"] is True
    assert r["message"]["role"] == "assistant"
    # random tiny model output is not a tool invocation → plain content
    assert "tool_calls" not in r["message"] or r["message"]["tool_calls"]


def test_bad_request_maps_to_400_not_500(stack):
    """Typed BadRequest from the service layer → 400; malformed options
    and undecodable images are the client's fault (round-1 advisor:
    internal ValueErrors must NOT be reclassified as 400s)."""
    name = _model_name(stack)
    post(stack["base"], "/api/pull", {"model": name}, stream=True)
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(stack["base"], "/api/generate",
             {"model": name, "prompt": "hi", "stream": False,
              "options": {"temperature": "hot"}})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(stack["base"], "/api/generate",
             {"model": name, "prompt": "hi", "stream": False,
              "images": ["!!!-not-an-image"]})
    assert ei.value.code == 400


def test_generate_suffix_fim(stack):
    """Ollama /api/generate `suffix` (fill-in-middle): renders through the
    template's .Suffix; models without one answer 400 (upstream parity)."""
    name = _model_name(stack)
    post(stack["base"], "/api/pull", {"model": name}, stream=True)
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(stack["base"], "/api/generate",
             {"model": name, "prompt": "def f(", "suffix": "return x",
              "stream": False})
    assert ei.value.code == 400
    tpl = "<PRE>{{ .Prompt }}<SUF>{{ .Suffix }}<MID>"
    post(stack["base"], "/api/create",
         {"model": "tiny-fim", "stream": False,
          "modelfile": f"FROM {name}\nTEMPLATE \"\"\"{tpl}\"\"\""})
    r = post(stack["base"], "/api/generate",
             {"model": "tiny-fim", "prompt": "p1", "suffix": "s1",
              "stream": False, "options": {"num_predict": 4}})
    assert r["done"] is True


def test_int4_server_generates(tmp_path):
    """--dtype int4 end-to-end over HTTP: pull -> transcode -> packed-int4
    quantize at load (app.py engine_dtype gate) -> /api/generate. On the
    CPU backend int4_mm_kernels keeps the portable XLA matmul path."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    gguf_path = str(tmp_path / "tiny.gguf")
    write_tiny_llama_gguf(gguf_path, cfg, params)
    reg = FakeRegistry()
    url = reg.start()
    with open(gguf_path, "rb") as f:
        reg.add_model("library", "tiny", "latest", f.read(),
                      params={"temperature": 0.0, "num_predict": 6})
    manager = ModelManager(str(tmp_path / "store"),
                           cache_dir=str(tmp_path / "cache"),
                           ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                             cache_dtype=jnp.float32,
                                             min_prefill_bucket=16),
                           engine_dtype="int4")
    httpd = serve(manager, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        name = f"http://{url.split('://')[1]}/library/tiny:latest"
        post(base, "/api/pull", {"model": name}, stream=True)
        out = post(base, "/api/generate",
                   {"model": name, "prompt": "t1 t2", "stream": False,
                    "options": {"num_predict": 6}})
        assert out["done"] and out["eval_count"] == 6
        from ollama_operator_tpu.ops.quant import is_int4
        lm = manager.loaded
        assert is_int4(lm.engine.params["layers"]["wq"])
    finally:
        httpd.shutdown()
        reg.stop()


def test_generate_mirostat_and_typical_options(stack):
    """mirostat/typical_p ride the Ollama options surface end-to-end:
    same seed → reproducible, and generation completes normally."""
    payload = {"model": _model_name(stack), "prompt": "t1 t2",
               "stream": False,
               "options": {"num_predict": 6, "temperature": 0.9,
                           "mirostat": 2, "mirostat_tau": 4.0,
                           "mirostat_eta": 0.2, "seed": 42}}
    r1 = post(stack["base"], "/api/generate", payload)
    r2 = post(stack["base"], "/api/generate", payload)
    assert r1["done"] and r1["eval_count"] >= 1
    assert r1["response"] == r2["response"]   # seeded mirostat reproduces
    r3 = post(stack["base"], "/api/generate",
              {"model": _model_name(stack), "prompt": "t1 t2",
               "stream": False,
               "options": {"num_predict": 4, "temperature": 1.0,
                           "typical_p": 0.8, "seed": 7}})
    assert r3["done"] and r3["eval_count"] >= 1


def test_blob_upload_and_create_from_digest(stack, tmp_path):
    """The `ollama create` CLI flow: HEAD /api/blobs/<digest> (404) →
    POST the GGUF bytes → HEAD (200) → /api/create with FROM @digest →
    the created model serves."""
    import hashlib
    base = stack["base"]
    # a GGUF the store has never seen: the fixture's pull already installed
    # tiny.gguf's digest, so re-uploading it would HEAD 200 from the start —
    # different init weights give different bytes, hence a fresh digest
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(123),
                                 dtype=jnp.float32)
    fresh_path = str(tmp_path / "fresh.gguf")
    write_tiny_llama_gguf(fresh_path, cfg, params)
    raw = open(fresh_path, "rb").read()
    digest = "sha256:" + hashlib.sha256(raw).hexdigest()
    assert not stack["manager"].store.has_blob(digest)

    def head(path):
        req = urllib.request.Request(base + path, method="HEAD")
        try:
            return urllib.request.urlopen(req, timeout=30).status
        except urllib.error.HTTPError as e:
            return e.code

    assert head(f"/api/blobs/{digest}") == 404
    req = urllib.request.Request(
        base + f"/api/blobs/{digest}", data=raw,
        headers={"Content-Type": "application/octet-stream"})
    assert urllib.request.urlopen(req, timeout=60).status == 201
    assert head(f"/api/blobs/{digest}") == 200

    # wrong digest must 400 and store nothing
    bad = "sha256:" + "0" * 64
    req = urllib.request.Request(base + f"/api/blobs/{bad}", data=b"junk",
                                 headers={"Content-Type":
                                          "application/octet-stream"})
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "mismatched digest accepted"
    except urllib.error.HTTPError as e:
        assert e.code == 400
    assert head(f"/api/blobs/{bad}") == 404

    # modelfile FROM @digest (the CLI's rewritten form)
    r = post(base, "/api/create",
             {"model": "fromblob", "stream": False,
              "modelfile": f"FROM @{digest}\n"
                           "TEMPLATE \"\"\"{{ .Prompt }}\"\"\"\n"
                           "PARAMETER temperature 0.0\n"
                           "PARAMETER num_predict 4"})
    assert r.get("status") == "success"
    r = post(base, "/api/generate",
             {"model": "fromblob", "prompt": "t1 t2", "stream": False,
              "options": {"num_predict": 3}})
    assert r["done"] and r["eval_count"] >= 1

    # newer create API: files dict referencing the same blob
    r = post(base, "/api/create",
             {"model": "fromfiles", "stream": False,
              "files": {"tiny.gguf": digest},
              "template": "{{ .Prompt }}",
              "parameters": {"num_predict": 4, "stop": ["zz"]}})
    assert r.get("status") == "success"
    shown = post(base, "/api/show", {"model": "fromfiles"})
    assert "num_predict" in shown["parameters"]


def test_create_from_missing_blob_is_400(stack):
    missing = "sha256:" + "ab" * 32
    try:
        post(stack["base"], "/api/create",
             {"model": "nope", "stream": False,
              "modelfile": f"FROM @{missing}"})
        assert False, "missing blob accepted"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_blob_digest_must_be_hex(stack):
    """A 64-char digest containing path separators must never reach the
    filesystem: blob_path() joins the digest into a path, so without hex
    validation HEAD is an existence oracle for arbitrary files and POST
    writes outside the blobs dir."""
    base = stack["base"]
    # 64 chars, right length, but a traversal payload — not hex
    evil = "/../" * 16
    assert len(evil) == 64
    req = urllib.request.Request(base + f"/api/blobs/sha256:{evil}",
                                 method="HEAD")
    try:
        status = urllib.request.urlopen(req, timeout=30).status
    except urllib.error.HTTPError as e:
        status = e.code
    assert status == 404

    req = urllib.request.Request(base + f"/api/blobs/sha256:{evil}",
                                 data=b"x" * 8,
                                 headers={"Content-Type":
                                          "application/octet-stream"})
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "non-hex digest accepted"
    except urllib.error.HTTPError as e:
        assert e.code == 400
    # uppercase hex is also rejected (store paths are lowercase-keyed)
    up = "AB" * 32
    req = urllib.request.Request(base + f"/api/blobs/sha256:{up}",
                                 data=b"x" * 8,
                                 headers={"Content-Type":
                                          "application/octet-stream"})
    try:
        urllib.request.urlopen(req, timeout=30)
        assert False, "uppercase digest accepted"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_blob_upload_eof_mid_body_does_not_hang(stack):
    """A client that disconnects before sending Content-Length bytes must
    not pin the handler thread: the drain/write loops treat read()==b'' as
    a short body and error out. Observable contract: the server keeps
    answering new requests and the half-uploaded digest is never stored."""
    import hashlib
    import socket
    base_host, base_port = stack["base"][len("http://"):].split(":")
    payload = b"y" * 4096
    digest = "sha256:" + hashlib.sha256(payload).hexdigest()
    for _ in range(2):   # fresh-path then (if stored) drain-path — never is
        s = socket.create_connection((base_host, int(base_port)), timeout=10)
        s.sendall(f"POST /api/blobs/{digest} HTTP/1.1\r\n"
                  f"Host: x\r\nContent-Length: {len(payload)}\r\n"
                  f"\r\n".encode() + payload[:100])
        s.close()   # EOF mid-body
    # server still serves, and the truncated upload was not promoted
    assert not stack["manager"].store.has_blob(digest)
    r = post(stack["base"], "/api/show", {"model": _model_name(stack)})
    assert "parameters" in r or "template" in r


# -- observability surface (ISSUE 7) -----------------------------------

def test_metrics_pass_strict_prometheus_validator(stack):
    """A live /metrics scrape — after real traffic — satisfies the strict
    text-format contract: HELP/TYPE on every series, monotone cumulative
    buckets, consistent _count/_sum (the CI metrics-lint check)."""
    from test_observability import validate_prometheus_text
    post(stack["base"], "/api/generate",
         {"model": _model_name(stack), "prompt": "warm",
          "options": {"num_predict": 3}}, stream=True)
    text = get(stack["base"], "/metrics")
    assert validate_prometheus_text(text) > 20
    # traffic + failure counters scrape as values even when idle
    for name in ("tpu_model_requests_total",
                 "tpu_model_preemptions_total",
                 "tpu_model_stream_frames_total",
                 "tpu_model_metrics_gauge_errors_total"):
        assert f"\n{name} " in text or text.startswith(f"{name} ")
    # the ISSUE-7 gauges registered in serve()
    assert "tpu_model_hbm_bytes_in_use" in text
    assert "tpu_model_flight_recorder_events" in text


def test_generate_timings_block_opt_in(stack):
    """options.trace=true adds a per-request timings summary to the final
    NDJSON frame; without it the frame shape is unchanged."""
    plain = post(stack["base"], "/api/generate",
                 {"model": _model_name(stack), "prompt": "a b",
                  "options": {"num_predict": 4}}, stream=True)
    assert "timings" not in plain[-1]
    lines = post(stack["base"], "/api/generate",
                 {"model": _model_name(stack), "prompt": "a b",
                  "options": {"num_predict": 4, "trace": True}},
                 stream=True)
    tm = lines[-1]["timings"]
    evs = {s["ev"] for s in tm["spans"]}
    assert {"queued", "admitted", "first_token", "finish"} <= evs
    assert "http_flush" in evs          # span reaches the HTTP write
    assert tm["queue_wait_ms"] >= 0
    assert tm["request_id"] >= 1


def test_debug_trace_endpoint(stack):
    lines = post(stack["base"], "/api/generate",
                 {"model": _model_name(stack), "prompt": "x y",
                  "options": {"num_predict": 3, "trace": True}},
                 stream=True)
    rid = lines[-1]["timings"]["request_id"]
    ids = json.loads(get(stack["base"], "/debug/trace"))["ids"]
    assert str(rid) in ids
    tl = json.loads(get(stack["base"], f"/debug/trace?id={rid}"))
    assert tl["id"] == str(rid)
    names = [e["ev"] for e in tl["events"]]
    assert "queued" in names and "finish" in names
    assert tl["events"][0]["t_ms"] >= 0
    with pytest.raises(urllib.error.HTTPError) as ei:
        get(stack["base"], "/debug/trace?id=99999999")
    assert ei.value.code == 404


def test_debug_events_endpoint(stack):
    post(stack["base"], "/api/generate",
         {"model": _model_name(stack), "prompt": "e1",
          "options": {"num_predict": 2}}, stream=True)
    body = json.loads(get(stack["base"], "/debug/events"))
    kinds = [e["kind"] for e in body["events"]]
    assert "admit" in kinds
    assert isinstance(body["dumps"], int)
    two = json.loads(get(stack["base"], "/debug/events?last=2"))["events"]
    assert len(two) == 2
    assert two == body["events"][-2:] or two[-1]["seq"] >= \
        body["events"][-1]["seq"]       # racing traffic may append


def test_debug_events_kind_filter(stack):
    post(stack["base"], "/api/generate",
         {"model": _model_name(stack), "prompt": "e2",
          "options": {"num_predict": 2}}, stream=True)
    body = json.loads(get(stack["base"], "/debug/events?kind=admit"))
    assert body["events"], "no admit events after a generate"
    assert all(e["kind"] == "admit" for e in body["events"])
    # kind filter applies BEFORE the last= trim: one admit-only row even
    # when the newest raw events are of other kinds
    one = json.loads(get(stack["base"],
                         "/debug/events?kind=admit&last=1"))["events"]
    assert len(one) == 1 and one[0]["kind"] == "admit"
    none = json.loads(get(stack["base"],
                          "/debug/events?kind=no_such_kind"))["events"]
    assert none == []


def test_debug_utilization_endpoint(stack):
    post(stack["base"], "/api/generate",
         {"model": _model_name(stack), "prompt": "u1 u2",
          "options": {"num_predict": 4}}, stream=True)
    body = json.loads(get(stack["base"], "/debug/utilization"))
    snap = body["snapshot"]
    assert snap["enabled"] is True
    assert snap["totals"]["useful_tokens"]["decode"] >= 4
    assert {"mfu", "occupancy", "waste_pct", "goodput_tok_s",
            "breakdown", "recompiles"} <= set(snap)
    # per-second ring rows are present and bounded by ?last=
    assert isinstance(body["ring"], list)
    short = json.loads(get(stack["base"], "/debug/utilization?last=3"))
    assert len(short["ring"]) <= 3


def test_api_ps_carries_utilization_block(stack):
    post(stack["base"], "/api/generate",
         {"model": _model_name(stack), "prompt": "p1",
          "options": {"num_predict": 2}}, stream=True)
    ps = json.loads(get(stack["base"], "/api/ps"))
    (m,) = [m for m in ps["models"] if m.get("utilization")]
    util = m["utilization"]
    assert util["enabled"] is True
    assert "mfu" in util and "occupancy" in util and "waste_pct" in util
    assert isinstance(util["recompiles"], dict)
    assert util["breakdown"]["wall_s"] > 0


def test_debug_profile_guarded(stack):
    """Profiling stalls the device queue: the endpoint must 403 unless
    TPU_DEBUG_PROFILE=1 opted the deployment in."""
    assert os.environ.get("TPU_DEBUG_PROFILE") != "1"
    with pytest.raises(urllib.error.HTTPError) as ei:
        get(stack["base"], "/debug/profile?seconds=0.2")
    assert ei.value.code == 403
