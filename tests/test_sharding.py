"""SPMD sharding tests on the 8-device virtual CPU mesh.

Verifies tp/dp-sharded execution is numerically identical to single-device
execution — the stand-in for multi-chip TPU slices (SURVEY.md §4
implication (b))."""

import jax
import jax.numpy as jnp
import numpy as np

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.parallel import (MeshPlan, make_mesh,
                                           set_mesh_compat, shard_params)
from ollama_operator_tpu.parallel.sharding import (
    kv_cache_pspec, params_sharding_tree)
from jax.sharding import NamedSharding, PartitionSpec as P


def tiny():
    return cfglib.PRESETS["tiny"]


def test_mesh_construction():
    mesh = make_mesh(MeshPlan(dp=2, sp=1, tp=4))
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "ep": 1, "tp": 4}


def test_tp_sharded_prefill_matches_single_device():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0,
                                cfg.vocab_size)
    ref, ref_k, _ = decoder.prefill_chunk(params, cfg, tokens)

    mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=4))
    with set_mesh_compat(mesh):
        sharded = shard_params(params, mesh)
        fn = jax.jit(lambda p, t: decoder.prefill_chunk(p, cfg, t))
        out, ks, _ = fn(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(ks), np.asarray(ref_k), rtol=2e-4,
                               atol=2e-4)


def test_dp_tp_sharded_decode_matches_single_device():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    B, S = 4, 16
    shape = (cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim)
    k_cache = jax.random.normal(jax.random.PRNGKey(2), shape, jnp.float32)
    v_cache = jax.random.normal(jax.random.PRNGKey(3), shape, jnp.float32)
    lengths = jnp.array([3, 5, 0, 7], jnp.int32)
    tokens = jax.random.randint(jax.random.PRNGKey(4), (B, 1), 0,
                                cfg.vocab_size)

    ref, ref_k, ref_v = decoder.forward_with_cache(params, cfg, tokens,
                                                   k_cache, v_cache, lengths)

    mesh = make_mesh(MeshPlan(dp=2, sp=1, tp=2))
    with set_mesh_compat(mesh):
        p_sh = shard_params(params, mesh)
        cache_sh = NamedSharding(mesh, kv_cache_pspec())
        kc = jax.device_put(k_cache, cache_sh)
        vc = jax.device_put(v_cache, cache_sh)
        fn = jax.jit(lambda p, t, k, v, l: decoder.forward_with_cache(
            p, cfg, t, k, v, l))
        out, k2, v2 = fn(p_sh, tokens, kc, vc, lengths)

    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(k2), np.asarray(ref_k), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(v2), np.asarray(ref_v), rtol=2e-4,
                               atol=2e-4)


def test_params_sharding_tree_covers_all_leaves():
    cfg = cfglib.ModelConfig(**{**tiny().__dict__, "attn_bias": True,
                                "out_bias": True, "qk_norm": True,
                                "norm_type": "layernorm"}).validate()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=8))
    tree = params_sharding_tree(params, mesh)
    flat_p, _ = jax.tree_util.tree_flatten(params)
    flat_s, _ = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, NamedSharding))
    assert len(flat_p) == len(flat_s)
