"""Fused prompt-lookup speculative decoding (ISSUE 6).

llama.cpp ships lookup decoding behind the reference's delegated engine;
here speculation is fused into the ONE batched decode dispatch
(``Engine.decode_n_launch(drafts=...)``): greedy penalty-free slots
accept their longest matching draft prefix plus a bonus token, everyone
else (sampling, constrained, penalized) gets exactly the token the normal
decode path would produce — in the same program. Coverage: the drafter
and accept/rollback units, engine-level acceptance semantics, bit-parity
with plain decode across tail buckets (greedy AND seeded sampling), with
and without a radix prefix hit, under mid-stream preempt/readmit, the
spec_ack host-length reconciliation, the async pipeline (cause="spec"
fallback counter must STAY zero), and the engine.step chaos drill during
a speculating dispatch.
"""

import dataclasses
import queue as queue_mod
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib, decoder
from ollama_operator_tpu.ops import sampling
from ollama_operator_tpu.runtime import drafter
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.scheduler import Request, Scheduler
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

CFG = dataclasses.replace(cfglib.PRESETS["tiny"], kernels="xla")
GREEDY = SlotOptions(temperature=0.0, repeat_penalty=1.0)
ECFG = EngineConfig(max_slots=2, max_seq_len=128, cache_dtype=jnp.float32,
                    min_prefill_bucket=16, decode_chunk=4)
PROMPT = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
# final bigram (7, 8) recurs, so the prompt-lookup drafter proposes from
# the very first dispatch — and the tiny model's greedy stream loops,
# so organic acceptance stays high for the duration of a test
LOOPY = np.array([7, 8, 9, 7, 8, 9, 7, 8], np.int32)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(CFG, jax.random.key(0), jnp.float32)


def _reference_tokens(params, n, opts=GREEDY):
    eng = Engine(CFG, params, ecfg=ECFG)
    seq = [eng.admit(0, PROMPT, opts)]
    for _ in range(n):
        seq.append(int(eng.decode()[0]))
    return seq


def _valid(row):
    return [int(t) for t in row if t < CFG.vocab_size]


def _spec_step(eng, drafts):
    """One fused speculative dispatch through the production surface:
    launch with drafts, wait, ack the host-length overshoot exactly like
    Scheduler._wait_handle. Returns rows transposed to [B, k+1]."""
    h = eng.decode_n_launch(drafts=np.asarray(drafts, np.int32))
    toks = h.wait()                                   # [k+1, B]
    rollback = np.maximum(h.budgets - h.accepted, 0)
    if rollback.any():
        eng.spec_ack(rollback)
    if eng.paged:
        eng._pt.retire_epoch(h.epoch)
    return toks.T


# ---------------------------------------------------------------------------
# host units: drafter + accept/rollback kernel
# ---------------------------------------------------------------------------

def test_drafter_propose_and_incremental_index():
    hist = [7, 8, 9, 7, 8, 9, 7, 8]
    idx, upto = {}, 0
    d, upto = drafter.propose(hist, idx, upto, 3)
    assert d == [9, 7, 8]                 # continuation of earlier (7, 8)
    assert upto == len(hist)              # every in-range continuation indexed
    # appending tokens extends the index incrementally and reproposes
    hist += [9, 7]
    d2, upto = drafter.propose(hist, idx, upto, 2)
    assert d2 == [8, 9]
    # no earlier occurrence → None; short history → None
    assert drafter.propose([1, 2, 3, 4], {}, 0, 3)[0] is None
    assert drafter.propose([1, 2], {}, 0, 3)[0] is None
    # latest occurrence wins (recency bias)
    h3 = [5, 6, 1, 5, 6, 2, 5, 6]
    d3, _ = drafter.propose(h3, {}, 0, 1)
    assert d3 == [2]
    # a match whose continuation runs off the end unrolls its period to
    # fill k — a greedy stream stuck on one token drafts k of it
    d4, _ = drafter.propose([1, 2, 3, 3, 3], {}, 0, 4)
    assert d4 == [3, 3, 3, 3]


def test_spec_accept_vectorized():
    drafts = jnp.array([[5, 6, 7], [5, 6, 7]], jnp.int32)
    greedy = jnp.array([[5, 6, 9, 4], [5, 6, 9, 4]], jnp.int32)
    ok = jnp.array([True, False])
    sampled = jnp.array([0, 42], jnp.int32)
    n_acc, out = sampling.spec_accept(drafts, greedy, ok, sampled, 100)
    # greedy row: 2 matching drafts + the model's own token as bonus
    assert n_acc.tolist() == [2, 0]
    assert out[0].tolist() == [5, 6, 9, 100]
    # non-greedy row accepts nothing and emits its sampled token
    assert out[1].tolist() == [42, 100, 100, 100]


# ---------------------------------------------------------------------------
# engine: fused acceptance semantics (migrated from the decode_spec era)
# ---------------------------------------------------------------------------

def test_correct_drafts_all_accepted(params):
    ref = _reference_tokens(params, 6)
    eng = Engine(CFG, params, ecfg=ECFG)
    first = eng.admit(0, PROMPT, GREEDY)
    assert first == ref[0]
    # draft exactly what the model will produce → all k accepted
    drafts = np.full((eng.n_slots, 3), 0, np.int32)
    drafts[0] = ref[1:4]
    toks = _spec_step(eng, drafts)
    got = _valid(toks[0])
    assert got == ref[1:5], (got, ref)          # 3 accepted + 1 bonus
    # after admit length == prompt (ref[0] pends in last_tokens); the
    # spec step wrote ref[0..3]'s K/V and advanced by the 4 emitted
    assert eng.slot_length(0) == len(PROMPT) + 4
    # spec_ack reconciled the launch-time over-advance back to truth
    assert int(eng._host_lengths[0]) == len(PROMPT) + 4
    # the engine continues correctly from the speculated state
    assert int(eng.decode()[0]) == ref[5]


def test_wrong_drafts_degrade_to_one_token(params):
    ref = _reference_tokens(params, 3)
    eng = Engine(CFG, params, ecfg=ECFG)
    eng.admit(0, PROMPT, GREEDY)
    bad = np.full((eng.n_slots, 3), (ref[1] + 1) % CFG.vocab_size, np.int32)
    toks = _spec_step(eng, bad)
    assert _valid(toks[0]) == [ref[1]]          # 0 accepted + bonus
    assert eng.slot_length(0) == len(PROMPT) + 1
    assert int(eng._host_lengths[0]) == len(PROMPT) + 1
    assert int(eng.decode()[0]) == ref[2]


def test_partial_acceptance(params):
    ref = _reference_tokens(params, 4)
    eng = Engine(CFG, params, ecfg=ECFG)
    eng.admit(0, PROMPT, GREEDY)
    drafts = np.zeros((eng.n_slots, 3), np.int32)
    drafts[0] = [ref[1], (ref[2] + 1) % CFG.vocab_size, ref[3]]
    toks = _spec_step(eng, drafts)
    # first draft accepted; second mismatches → bonus = the real ref[2]
    assert _valid(toks[0]) == ref[1:3]
    assert int(eng.decode()[0]) == ref[3]


def test_state_matches_token_by_token_decode(params):
    """Counts/pring/lengths after a spec step must equal the state after
    the same tokens emitted one decode() at a time (the penalty ring sees
    identical positions)."""
    ref = _reference_tokens(params, 5)

    eng_a = Engine(CFG, params, ecfg=ECFG)   # token-by-token
    eng_a.admit(0, PROMPT, GREEDY)
    for _ in range(4):
        eng_a.decode()

    eng_b = Engine(CFG, params, ecfg=ECFG)   # speculative
    eng_b.admit(0, PROMPT, GREEDY)
    drafts = np.zeros((eng_b.n_slots, 3), np.int32)
    drafts[0] = ref[1:4]
    _spec_step(eng_b, drafts)

    np.testing.assert_array_equal(np.asarray(eng_a.lengths),
                                  np.asarray(eng_b.lengths))
    np.testing.assert_array_equal(np.asarray(eng_a.counts),
                                  np.asarray(eng_b.counts))
    np.testing.assert_array_equal(np.asarray(eng_a.last_tokens),
                                  np.asarray(eng_b.last_tokens))
    np.testing.assert_array_equal(np.asarray(eng_a.pring),
                                  np.asarray(eng_b.pring))


def test_sampling_slot_gets_normal_token(params):
    """A non-greedy slot in the same batch accepts nothing and samples
    exactly what decode() would (same per-step PRNG fold)."""
    sample_opts = SlotOptions(temperature=0.9, seed=7)
    eng_a = Engine(CFG, params, ecfg=ECFG)
    eng_a.admit(0, PROMPT, GREEDY)
    eng_a.admit(1, PROMPT[:5], sample_opts)
    want = int(eng_a.decode()[1])

    eng_b = Engine(CFG, params, ecfg=ECFG)
    eng_b.admit(0, PROMPT, GREEDY)
    eng_b.admit(1, PROMPT[:5], sample_opts)
    toks = _spec_step(eng_b, np.zeros((2, 2), np.int32))
    row1 = _valid(toks[1])
    assert len(row1) == 1 and row1[0] == want


def test_penalized_greedy_excluded_from_acceptance(params):
    """repeat_penalty != 1.0 makes raw-argmax acceptance inexact — the
    slot must fall back to the (penalty-aware) single-token path."""
    pen = SlotOptions(temperature=0.0, repeat_penalty=1.8)
    eng_a = Engine(CFG, params, ecfg=ECFG)
    eng_a.admit(0, PROMPT, pen)
    want = int(eng_a.decode()[0])

    eng_b = Engine(CFG, params, ecfg=ECFG)
    eng_b.admit(0, PROMPT, pen)
    drafts = np.full((eng_b.n_slots, 3), want, np.int32)
    toks = _spec_step(eng_b, drafts)
    assert _valid(toks[0]) == [want]            # exactly one, exact token


def test_paged_spec_decode(params):
    ref = _reference_tokens(params, 4)
    eng = Engine(CFG, params,
                 ecfg=dataclasses.replace(ECFG, paged=True, page_size=8))
    eng.admit(0, PROMPT, GREEDY)
    drafts = np.zeros((eng.n_slots, 3), np.int32)
    drafts[0] = ref[1:4]
    toks = _spec_step(eng, drafts)
    assert _valid(toks[0]) == ref[1:5]
    assert eng.quarantined_pages == 0
    assert int(eng.decode()[0]) == ref[5] if len(ref) > 5 else True


def test_spec_warm_preseeds_dispatch_gauge(params, monkeypatch):
    """warm_buckets compiles every (k, bucket) spec program AND runs one
    no-op spec dispatch over the empty batch, so dispatch_ms["spec"]
    starts at steady-state launch cost — the first real request must
    never eat the compile (the BENCH_r05 623 ms anomaly)."""
    monkeypatch.setenv("TPU_SPEC_DECODE", "3")
    eng = Engine(CFG, params, ecfg=dataclasses.replace(
        ECFG, max_seq_len=32))              # 2 buckets keeps the warm cheap
    eng.warm_buckets()
    n_warmed = len(eng._spec_execs)
    assert n_warmed >= 2                    # every bucket, not just one
    assert eng.dispatch_ms["spec"] > 0.0    # pre-seeded by the no-op pass
    # the warm dispatch left no state behind: admission still clean
    ref = _reference_tokens(params, 1)
    assert eng.admit(0, PROMPT, GREEDY) == ref[0]
    drafts = np.zeros((eng.n_slots, 3), np.int32)
    drafts[0] = ref[1:2] + [0, 0]
    _spec_step(eng, drafts)
    assert len(eng._spec_execs) == n_warmed     # no mid-serving compile


# ---------------------------------------------------------------------------
# scheduler: bit-parity with plain decode (the acceptance criterion)
# ---------------------------------------------------------------------------

def _run_sched(params, monkeypatch, spec_k, *, ecfg=None, prompts=None,
               opts=None, max_tokens=40, async_dispatch=None):
    """One scheduler lifetime; returns (per-request token streams, sched
    stats dict). Parity tests run this twice — TPU_SPEC_DECODE=0 vs k —
    and require identical streams."""
    monkeypatch.setenv("TPU_SPEC_DECODE", str(spec_k))
    eng = Engine(CFG, params, ecfg=ecfg or ECFG)
    kw = {} if async_dispatch is None else {"async_dispatch": async_dispatch}
    sched = Scheduler(eng, **kw)
    try:
        reqs = [sched.submit(p, opts=o, max_tokens=max_tokens)
                for p, o in zip(prompts, opts)]
        outs = [list(r.tokens()) for r in reqs]
        for r in reqs:
            assert r.error is None, r.error
        stats = {"drafted": sched.spec_drafted,
                 "accepted": sched.spec_accepted,
                 "n_preempt": sched.n_preemptions,
                 "reused": [r.stats.n_reused for r in reqs]}
    finally:
        sched.shutdown()
    return outs, stats


@pytest.mark.parametrize("paged", [False, True],
                         ids=["dense", "paged"])
def test_sched_parity_across_tail_buckets(params, monkeypatch, paged):
    """Greedy + seeded sampling side by side through the REAL drafter,
    generating far enough to cross several attention buckets (16→32→64→
    128): accepted streams must be bit-identical to the non-speculative
    run, for both slots, and speculation must actually engage."""
    ecfg = (dataclasses.replace(ECFG, paged=True, page_size=8)
            if paged else ECFG)
    prompts = [LOOPY, PROMPT]
    opts = [GREEDY, SlotOptions(temperature=0.8, seed=11)]
    base, _ = _run_sched(params, monkeypatch, 0, ecfg=ecfg,
                         prompts=prompts, opts=opts, max_tokens=70)
    spec, st = _run_sched(params, monkeypatch, 3, ecfg=ecfg,
                          prompts=prompts, opts=opts, max_tokens=70)
    assert spec == base
    assert all(len(o) == 70 for o in spec)
    assert st["drafted"] > 0                 # the drafter found matches
    assert 0 < st["accepted"] <= st["drafted"]


def test_sched_parity_sync_dispatch(params, monkeypatch):
    """TPU_ASYNC_DISPATCH=0: the sync spec path (launch + immediate
    wait + ack) produces the same stream as async and as plain decode."""
    prompts, opts = [LOOPY, LOOPY], [GREEDY, GREEDY]
    base, _ = _run_sched(params, monkeypatch, 0, prompts=prompts,
                         opts=opts, async_dispatch=False)
    spec, st = _run_sched(params, monkeypatch, 3, prompts=prompts,
                          opts=opts, async_dispatch=False)
    assert spec == base
    assert st["drafted"] > 0


def test_sched_parity_with_radix_prefix_hit(params, monkeypatch):
    """A speculating request admitted THROUGH a radix prefix hit (page
    stitch instead of prefill) must still stream bit-identically: the
    fused dispatch sees only lengths, never how the prefix arrived."""
    ecfg = dataclasses.replace(ECFG, paged=True, page_size=8)
    prefix = np.concatenate([LOOPY, LOOPY, np.array([7, 8], np.int32)])

    def run(spec_k):
        monkeypatch.setenv("TPU_SPEC_DECODE", str(spec_k))
        eng = Engine(CFG, params, ecfg=ecfg)
        sched = Scheduler(eng)
        try:
            cold = list(sched.submit(prefix, opts=GREEDY,
                                     max_tokens=24).tokens())
            hit = sched.submit(prefix, opts=GREEDY, max_tokens=24)
            warm = list(hit.tokens())
            reused = hit.stats.n_reused
        finally:
            sched.shutdown()
        return cold, warm, reused

    cold0, warm0, _ = run(0)
    cold1, warm1, reused = run(3)
    assert reused > 0                        # the hit actually happened
    assert cold1 == cold0 and warm1 == warm0
    assert warm0 == cold0                    # hit is invisible to content


def test_sched_parity_under_preempt_readmit(params, monkeypatch):
    """Pool pressure mid-stream: a speculating request preempted and
    re-admitted (resume_ids re-prefill) continues bit-identically — the
    drafter's incremental index survives the round trip because it is
    keyed on (prompt + all_tokens) positions, which re-admission
    preserves."""
    ecfg = EngineConfig(max_slots=3, max_seq_len=128,
                        cache_dtype=jnp.float32, min_prefill_bucket=16,
                        decode_chunk=4, paged=True, page_size=8,
                        n_pages=8)
    prompts = [LOOPY, LOOPY + 1, LOOPY + 2]
    opts = [GREEDY] * 3
    base, st0 = _run_sched(params, monkeypatch, 0, ecfg=ecfg,
                           prompts=prompts, opts=opts, max_tokens=16)
    spec, st1 = _run_sched(params, monkeypatch, 3, ecfg=ecfg,
                           prompts=prompts, opts=opts, max_tokens=16)
    assert spec == base
    # 3 slots × (8 prompt + 16 gen) = 72 token places > 64 page slots →
    # pressure must have preempted (or evicted) in both runs
    assert st0["n_preempt"] >= 1 and st1["n_preempt"] >= 1


def test_async_spec_no_fallback_and_acceptance_metrics(params, monkeypatch):
    """With TPU_ASYNC_DISPATCH=1 the spec path double-buffers: the
    cause="spec" fallback counter STAYS at zero (it exists only to prove
    that), and the drafted/accepted counters advance together."""
    before_fb = METRICS.get("tpu_model_async_fallback_total",
                            '{cause="spec"}')
    before_d = METRICS.get("tpu_model_spec_drafted_tokens_total")
    before_a = METRICS.get("tpu_model_spec_accepted_tokens_total")
    spec, st = _run_sched(params, monkeypatch, 3,
                          prompts=[LOOPY, LOOPY], opts=[GREEDY, GREEDY],
                          async_dispatch=True)
    assert METRICS.get("tpu_model_async_fallback_total",
                       '{cause="spec"}') == before_fb
    d = METRICS.get("tpu_model_spec_drafted_tokens_total") - before_d
    a = METRICS.get("tpu_model_spec_accepted_tokens_total") - before_a
    assert d == st["drafted"] > 0
    assert a == st["accepted"] > 0
    assert a <= d


def test_scheduler_spec_oracle_end_to_end(params, monkeypatch):
    """TPU_SPEC_DECODE=3 through the real scheduler with an ORACLE
    drafter (the base run's own continuation), pinning deterministic
    full acceptance: the stream must be IDENTICAL to the
    non-speculative run — speculation may only change speed."""
    prompt = np.array([7, 8, 9, 7, 8, 9, 7, 8], np.int32)

    def run(spec, base=None):
        monkeypatch.setenv("TPU_SPEC_DECODE", "3" if spec else "0")
        if base is not None:
            def oracle(req, k, ngram=drafter.NGRAM, extra=None):
                done = len(req.all_tokens) + len(extra or ())
                return base[done: done + k] or None
            monkeypatch.setattr(Scheduler, "_lookup_draft",
                                staticmethod(oracle))
        eng = Engine(CFG, params, ecfg=ECFG)
        sched = Scheduler(eng)
        try:
            req = sched.submit(prompt, GREEDY, max_tokens=24,
                               eog_ids=frozenset())
            toks = list(req.tokens())
        finally:
            sched.shutdown()
        return toks, len(eng._spec_execs)

    base, n_spec_base = run(False)
    assert len(base) == 24 and n_spec_base == 0
    spec, n_spec = run(True, base=base)
    assert spec == base, (base, spec)
    assert n_spec >= 1          # the spec program actually dispatched


def test_lookup_draft_matches_ngram():
    req = Request(np.array([7, 8, 9, 7, 8, 9, 7, 8], np.int32),
                  GREEDY, 8, frozenset())
    assert [int(t) for t in Scheduler._lookup_draft(req, 3)] == [9, 7, 8]
    req2 = Request(np.array([1, 2, 3], np.int32), GREEDY, 8, frozenset())
    assert Scheduler._lookup_draft(req2, 3) is None
    # generated tokens extend the searchable history
    req.all_tokens = [9, 7]
    assert [int(t) for t in Scheduler._lookup_draft(req, 2)] == [8, 9]
    # tokens delivered but not yet fanned out (async spec pipelining)
    # extend it further without corrupting the incremental index —
    # _fanout then appends exactly those tokens, so the positions the
    # extra call indexed stay valid and the next plain call agrees
    assert [int(t) for t in
            Scheduler._lookup_draft(req, 2, extra=[8, 9])] == [7, 8]
    req.all_tokens += [8, 9]
    assert [int(t) for t in Scheduler._lookup_draft(req, 2)] == [7, 8]


# ---------------------------------------------------------------------------
# chaos: engine.step fault during a speculating dispatch
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_engine_step_fault_during_spec_exactly_once(params, monkeypatch):
    """CI chaos drill 5: engine.step fail:after=1 in paged+async with
    TPU_SPEC_DECODE on and a prompt the drafter matches immediately —
    the failing launch IS a speculating dispatch with another pending.
    Every owner gets exactly ONE terminal error, the supervised restart
    drains the quarantine, the page table checks clean, and serving
    (still speculating) resumes."""
    monkeypatch.setenv("TPU_SPEC_DECODE", "3")
    # replay off: this drill pins the exactly-once ERROR contract (the
    # zero-error replay drill lives in test_lifecycle.py)
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    eng = Engine(CFG, params, ecfg=dataclasses.replace(
        ECFG, paged=True, page_size=8))
    sched = Scheduler(eng, restart_backoff=0.001, async_dispatch=True)
    try:
        assert sched.async_dispatch
        FAULTS.arm("engine.step", "fail:after=1")
        reqs = [sched.submit(LOOPY + i, max_tokens=48, opts=GREEDY)
                for i in range(2)]
        errs = 0
        for r in reqs:
            try:
                assert len(list(r.tokens())) <= 48
            except RuntimeError as e:
                assert "engine.step" in str(e)
                errs += 1
            # exactly once: nothing queued after the terminal item
            with pytest.raises(queue_mod.Empty):
                r.out.get_nowait()
        assert errs == 2                       # both owners errored
        FAULTS.disarm("engine.step")
        t1 = time.monotonic() + 5
        while sched.n_restarts < 1 and time.monotonic() < t1:
            time.sleep(0.01)
        assert sched.n_restarts >= 1 and not sched.broken
        # the restart drained everything: whole pool reclaimable
        assert eng.quarantined_pages == 0
        assert eng.free_pages == eng._pt.data_pages
        eng._pt.check()
        r2 = sched.submit(LOOPY, max_tokens=12, opts=GREEDY)
        assert len(list(r2.tokens())) == 12
        assert sched.spec_drafted > 0          # speculation resumed
    finally:
        FAULTS.disarm("engine.step")
        sched.shutdown()
