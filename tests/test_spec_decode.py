"""Speculative decoding (prompt-lookup verify step, engine.decode_spec).

llama.cpp ships lookup decoding behind the reference's delegated engine;
here the verify step is ONE jitted dispatch over the whole slot batch:
greedy penalty-free slots accept their longest matching draft prefix plus
a bonus token, everyone else (sampling, constrained, penalized) gets
exactly the token the normal decode path would produce.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib, decoder
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

CFG = dataclasses.replace(cfglib.PRESETS["tiny"], kernels="xla")
GREEDY = SlotOptions(temperature=0.0, repeat_penalty=1.0)
ECFG = EngineConfig(max_slots=2, max_seq_len=128, cache_dtype=jnp.float32,
                    min_prefill_bucket=16, decode_chunk=4)
PROMPT = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(CFG, jax.random.key(0), jnp.float32)


def _reference_tokens(params, n, opts=GREEDY):
    eng = Engine(CFG, params, ecfg=ECFG)
    seq = [eng.admit(0, PROMPT, opts)]
    for _ in range(n):
        seq.append(int(eng.decode()[0]))
    return seq


def _valid(row):
    return [int(t) for t in row if t < CFG.vocab_size]


def test_correct_drafts_all_accepted(params):
    ref = _reference_tokens(params, 6)
    eng = Engine(CFG, params, ecfg=ECFG)
    first = eng.admit(0, PROMPT, GREEDY)
    assert first == ref[0]
    # draft exactly what the model will produce → all k accepted
    drafts = np.full((eng.n_slots, 3), 0, np.int32)
    drafts[0] = ref[1:4]
    toks = eng.decode_spec(drafts)
    got = _valid(toks[0])
    assert got == ref[1:5], (got, ref)          # 3 accepted + 1 bonus
    # after admit length == prompt (ref[0] pends in last_tokens); the
    # spec step wrote ref[0..3]'s K/V and advanced by the 4 emitted
    assert eng.slot_length(0) == len(PROMPT) + 4
    # the engine continues correctly from the speculated state
    assert int(eng.decode()[0]) == ref[5]


def test_wrong_drafts_degrade_to_one_token(params):
    ref = _reference_tokens(params, 3)
    eng = Engine(CFG, params, ecfg=ECFG)
    eng.admit(0, PROMPT, GREEDY)
    bad = np.full((eng.n_slots, 3), (ref[1] + 1) % CFG.vocab_size, np.int32)
    toks = eng.decode_spec(bad)
    assert _valid(toks[0]) == [ref[1]]          # 0 accepted + bonus
    assert eng.slot_length(0) == len(PROMPT) + 1
    assert int(eng.decode()[0]) == ref[2]


def test_partial_acceptance(params):
    ref = _reference_tokens(params, 4)
    eng = Engine(CFG, params, ecfg=ECFG)
    eng.admit(0, PROMPT, GREEDY)
    drafts = np.zeros((eng.n_slots, 3), np.int32)
    drafts[0] = [ref[1], (ref[2] + 1) % CFG.vocab_size, ref[3]]
    toks = eng.decode_spec(drafts)
    # first draft accepted; second mismatches → bonus = the real ref[2]
    assert _valid(toks[0]) == ref[1:3]
    assert int(eng.decode()[0]) == ref[3]


def test_state_matches_token_by_token_decode(params):
    """Counts/pring/lengths after a spec step must equal the state after
    the same tokens emitted one decode() at a time (the penalty ring sees
    identical positions)."""
    ref = _reference_tokens(params, 5)

    eng_a = Engine(CFG, params, ecfg=ECFG)   # token-by-token
    eng_a.admit(0, PROMPT, GREEDY)
    for _ in range(4):
        eng_a.decode()

    eng_b = Engine(CFG, params, ecfg=ECFG)   # speculative
    eng_b.admit(0, PROMPT, GREEDY)
    drafts = np.zeros((eng_b.n_slots, 3), np.int32)
    drafts[0] = ref[1:4]
    eng_b.decode_spec(drafts)

    np.testing.assert_array_equal(np.asarray(eng_a.lengths),
                                  np.asarray(eng_b.lengths))
    np.testing.assert_array_equal(np.asarray(eng_a.counts),
                                  np.asarray(eng_b.counts))
    np.testing.assert_array_equal(np.asarray(eng_a.last_tokens),
                                  np.asarray(eng_b.last_tokens))
    np.testing.assert_array_equal(np.asarray(eng_a.pring),
                                  np.asarray(eng_b.pring))


def test_sampling_slot_gets_normal_token(params):
    """A non-greedy slot in the same batch accepts nothing and samples
    exactly what decode() would (same per-step PRNG fold)."""
    sample_opts = SlotOptions(temperature=0.9, seed=7)
    eng_a = Engine(CFG, params, ecfg=ECFG)
    eng_a.admit(0, PROMPT, GREEDY)
    eng_a.admit(1, PROMPT[:5], sample_opts)
    want = int(eng_a.decode()[1])

    eng_b = Engine(CFG, params, ecfg=ECFG)
    eng_b.admit(0, PROMPT, GREEDY)
    eng_b.admit(1, PROMPT[:5], sample_opts)
    toks = eng_b.decode_spec(np.zeros((2, 2), np.int32))
    row1 = _valid(toks[1])
    assert len(row1) == 1 and row1[0] == want


def test_penalized_greedy_excluded_from_acceptance(params):
    """repeat_penalty != 1.0 makes raw-argmax acceptance inexact — the
    slot must fall back to the (penalty-aware) single-token path."""
    pen = SlotOptions(temperature=0.0, repeat_penalty=1.8)
    eng_a = Engine(CFG, params, ecfg=ECFG)
    eng_a.admit(0, PROMPT, pen)
    want = int(eng_a.decode()[0])

    eng_b = Engine(CFG, params, ecfg=ECFG)
    eng_b.admit(0, PROMPT, pen)
    drafts = np.full((eng_b.n_slots, 3), want, np.int32)
    toks = eng_b.decode_spec(drafts)
    assert _valid(toks[0]) == [want]            # exactly one, exact token


def test_paged_spec_decode(params):
    ref = _reference_tokens(params, 4)
    eng = Engine(CFG, params,
                 ecfg=dataclasses.replace(ECFG, paged=True, page_size=8))
    eng.admit(0, PROMPT, GREEDY)
    drafts = np.zeros((eng.n_slots, 3), np.int32)
    drafts[0] = ref[1:4]
    toks = eng.decode_spec(drafts)
    assert _valid(toks[0]) == ref[1:5]
    assert int(eng.decode()[0]) == ref[5] if len(ref) > 5 else True


def test_scheduler_spec_end_to_end(params, monkeypatch):
    """TPU_SPEC_DECODE=3 through the real scheduler: the generated
    stream must be IDENTICAL to the non-speculative run — speculation may
    only change speed. Drafting uses an oracle (the base run's own
    continuation) so acceptance is deterministic; the production
    prompt-lookup drafter is covered by test_lookup_draft below (the
    tiny random model's outputs never repeat an n-gram, so organic
    matches can't be forced here)."""
    from ollama_operator_tpu.runtime.scheduler import Scheduler

    prompt = np.array([7, 8, 9, 7, 8, 9, 7, 8], np.int32)

    def run(spec, base=None):
        monkeypatch.setenv("TPU_SPEC_DECODE", "3" if spec else "0")
        if base is not None:
            monkeypatch.setattr(
                Scheduler, "_lookup_draft",
                staticmethod(lambda req, k, ngram=2:
                             base[len(req.all_tokens):
                                  len(req.all_tokens) + k]))
        eng = Engine(CFG, params, ecfg=ECFG)
        sched = Scheduler(eng)
        try:
            req = sched.submit(prompt, GREEDY, max_tokens=24,
                               eog_ids=frozenset())
            toks = list(req.tokens())
        finally:
            sched.shutdown()
        return toks, len(eng._spec_execs)

    base, n_spec_base = run(False)
    assert len(base) == 24 and n_spec_base == 0
    spec, n_spec = run(True, base=base)
    assert spec == base, (base, spec)
    assert n_spec >= 1          # the spec program actually dispatched


def test_lookup_draft_matches_ngram():
    from ollama_operator_tpu.runtime.scheduler import Request, Scheduler
    req = Request(np.array([7, 8, 9, 7, 8, 9, 7, 8], np.int32),
                  GREEDY, 8, frozenset())
    assert [int(t) for t in Scheduler._lookup_draft(req, 3)] == [9, 7, 8]
    req2 = Request(np.array([1, 2, 3], np.int32), GREEDY, 8, frozenset())
    assert Scheduler._lookup_draft(req2, 3) is None
    # generated tokens extend the searchable history
    req.all_tokens = [9, 7]
    assert [int(t) for t in Scheduler._lookup_draft(req, 2)] == [8, 9]
