"""Stall-free continuous batching (chunked prefill + double-buffered
async dispatch + batched admission).

The invariants under test:
- chunked admission is BIT-IDENTICAL to one-shot admission (every prompt
  length, with and without prefix-cache reuse, and across a
  preempt-and-readmit mid-prefill) — the final piece's PRNG seed derives
  from (slot, full prompt length), same as a one-shot admit;
- async double-buffered dispatch delivers the same streams in the same
  order as synchronous dispatch;
- batched same-bucket admission (admit_many) matches per-slot admits;
- a supervisor restart mid-pipeline (async decode in flight, or a
  chunked prefill mid-piece) errors each in-flight request exactly once
  and the next request serves normally;
- an exhausted max_tokens budget finishes with done_reason "length"
  (Ollama semantics: truncation, not a natural stop).
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                SlotOptions)
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.paged import PagesExhausted
from ollama_operator_tpu.runtime.scheduler import Scheduler
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

GREEDY = SlotOptions(temperature=0.0, repeat_penalty=1.0)


@pytest.fixture(scope="module")
def eng():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    return Engine(cfg, params,
                  ecfg=EngineConfig(max_slots=4, max_seq_len=64,
                                    cache_dtype=jnp.float32,
                                    min_prefill_bucket=16))


@pytest.fixture(autouse=True)
def _clean_slots(eng):
    yield
    for s in range(eng.n_slots):
        eng.release(s)


def prompt(n, base=1):
    return ((np.arange(n) + base) % 50 + 1).astype(np.int32)


def run_one(eng, ids, *, prefill_chunk, async_dispatch, max_tokens=6):
    """One request through a fresh scheduler; returns (tokens, reason)."""
    sched = Scheduler(eng, prefill_chunk=prefill_chunk,
                      async_dispatch=async_dispatch)
    try:
        r = sched.submit(np.asarray(ids, np.int32), GREEDY,
                         max_tokens=max_tokens)
        toks = list(r.tokens())
        return toks, r.done_reason
    finally:
        sched.shutdown()
        for s in range(eng.n_slots):
            eng.release(s)


def manual(sched):
    """Stop the loop thread so tests can drive _step() deterministically."""
    sched._stop.set()
    sched._wake.set()
    sched._thread.join(timeout=5)
    return sched


# ---------------------------------------------------------------- parity

@pytest.mark.parametrize("plen", [17, 24, 33, 40, 48])
def test_chunked_admission_parity(eng, plen):
    """Chunked admission (16-token pieces) streams the exact one-shot
    tokens for every prompt length spanning the bucket ladder."""
    ids = prompt(plen)
    base, base_reason = run_one(eng, ids, prefill_chunk=0,
                                async_dispatch=False)
    c0 = METRICS.get("tpu_model_prefill_chunks_total")
    chunked, reason = run_one(eng, ids, prefill_chunk=16,
                              async_dispatch=False)
    assert chunked == base
    assert reason == base_reason
    # first piece + at least one interleaved piece actually ran
    assert METRICS.get("tpu_model_prefill_chunks_total") - c0 >= 2


def test_chunked_prefix_reuse_parity(eng):
    """A chunked admission whose first piece reuses a parked prefix
    (engine.extend from the parked length) still matches one-shot."""
    p1 = prompt(20)
    sched = Scheduler(eng, prefill_chunk=16, async_dispatch=False)
    try:
        r1 = sched.submit(p1, GREEDY, max_tokens=4)
        out1 = list(r1.tokens())
        # continuation prompt: the parked tokens plus a >1-piece tail
        p2 = np.concatenate([p1, np.asarray(out1, np.int32),
                             prompt(20, base=30)])
        r2 = sched.submit(p2, GREEDY, max_tokens=4)
        out2 = list(r2.tokens())
        assert r2.stats.n_reused >= Scheduler.MIN_PREFIX_REUSE
    finally:
        sched.shutdown()
        for s in range(eng.n_slots):
            eng.release(s)
    base, _ = run_one(eng, p2, prefill_chunk=0, async_dispatch=False,
                      max_tokens=4)
    assert out2 == base


def test_preempt_mid_chunked_prefill_readmits(eng, monkeypatch):
    """PagesExhausted on an interleaved piece requeues the request; the
    re-admission restarts the prompt and the stream is still exactly the
    one-shot stream (no tokens were emitted before the preempt)."""
    ids = prompt(40)
    base, _ = run_one(eng, ids, prefill_chunk=0, async_dispatch=False)
    calls = {"n": 0}
    orig = eng.extend

    def flaky(slot, full_ids, start, *a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise PagesExhausted("injected mid-prefill pool pressure")
        return orig(slot, full_ids, start, *a, **kw)

    monkeypatch.setattr(eng, "extend", flaky)
    out, reason = run_one(eng, ids, prefill_chunk=16, async_dispatch=False)
    assert calls["n"] >= 2           # the preempted piece was retried
    assert out == base
    assert reason in ("stop", "length")


def test_async_dispatch_parity(eng):
    """Double-buffered dispatch: same streams, same order, as sync."""
    prompts = [prompt(6 + 3 * i, base=7 * i) for i in range(4)]
    outs = {}
    for async_d in (False, True):
        sched = Scheduler(eng, prefill_chunk=0, async_dispatch=async_d)
        try:
            reqs = [sched.submit(p, GREEDY, max_tokens=9) for p in prompts]
            outs[async_d] = [list(r.tokens()) for r in reqs]
        finally:
            sched.shutdown()
            for s in range(eng.n_slots):
                eng.release(s)
    assert outs[True] == outs[False]
    assert all(len(o) == 9 for o in outs[True])


def test_chunk_frames_arrive_in_order(eng):
    """Per-dispatch frames under async dispatch concatenate to the token
    stream (no reorder, no duplicate, no loss)."""
    sched = Scheduler(eng, prefill_chunk=0, async_dispatch=True)
    try:
        r = sched.submit(prompt(8), GREEDY, max_tokens=20)
        frames = list(r.chunks())
        flat = [t for f in frames for t in f]
        assert len(flat) == 20
        assert flat == r.all_tokens[:20]
        assert r.done_reason == "length"
    finally:
        sched.shutdown()


def test_interleaved_prefill_keeps_decoders_running(eng):
    """A long chunked admission interleaves with active decoders: every
    stream still matches its solo greedy run (per-slot rows are
    independent), and the decoders keep producing between pieces."""
    bg1, bg2, long_p = prompt(6), prompt(9, base=11), prompt(44, base=3)
    base_bg1, _ = run_one(eng, bg1, prefill_chunk=0, async_dispatch=False,
                          max_tokens=16)
    base_bg2, _ = run_one(eng, bg2, prefill_chunk=0, async_dispatch=False,
                          max_tokens=16)
    base_long, _ = run_one(eng, long_p, prefill_chunk=0,
                           async_dispatch=False, max_tokens=4)
    sched = Scheduler(eng, prefill_chunk=16, async_dispatch=True)
    try:
        r1 = sched.submit(bg1, GREEDY, max_tokens=16)
        r2 = sched.submit(bg2, GREEDY, max_tokens=16)
        time.sleep(0.05)           # let the decoders start
        rl = sched.submit(long_p, GREEDY, max_tokens=4)
        assert list(r1.tokens()) == base_bg1
        assert list(r2.tokens()) == base_bg2
        assert list(rl.tokens()) == base_long
    finally:
        sched.shutdown()
        for s in range(eng.n_slots):
            eng.release(s)


# ------------------------------------------------------ batched admission

def test_admit_many_matches_single_admits(eng):
    """One batched prefill dispatch == per-slot admits: same first
    tokens, same cache state (verified by decoding a chunk after)."""
    p1, p2 = prompt(14), prompt(11, base=23)
    t1 = eng.admit(0, p1, GREEDY)
    t2 = eng.admit(1, p2, GREEDY)
    rows_single = np.asarray(eng.decode_n(8))[:, :2].copy()
    for s in range(eng.n_slots):
        eng.release(s)
    toks = eng.admit_many([0, 1], [p1, p2], [GREEDY, GREEDY])
    assert toks == [t1, t2]
    rows_batched = np.asarray(eng.decode_n(8))[:, :2]
    np.testing.assert_array_equal(rows_batched, rows_single)


def test_scheduler_batches_same_bucket_admissions(eng, monkeypatch):
    """Several same-bucket waiters admit in ONE admit_many dispatch, and
    their streams match sequential one-shot runs."""
    prompts = [prompt(10, base=5 * i) for i in range(4)]
    bases = [run_one(eng, p, prefill_chunk=0, async_dispatch=False,
                     max_tokens=5)[0] for p in prompts]
    calls = []
    orig = eng.admit_many

    def spy(slots, ids_list, opts_list=None):
        calls.append(list(slots))
        return orig(slots, ids_list, opts_list)

    monkeypatch.setattr(eng, "admit_many", spy)
    sched = manual(Scheduler(eng, prefill_chunk=0, async_dispatch=False))
    try:
        reqs = [sched.submit(p, GREEDY, max_tokens=5) for p in prompts]
        for _ in range(64):
            sched._step()
            if (all(sched._running[s] is None
                    for s in range(eng.n_slots))
                    and sched._admission.empty()
                    and not sched._prefilling):
                break
        outs = [list(r.tokens()) for r in reqs]
    finally:
        sched.shutdown()
        for s in range(eng.n_slots):
            eng.release(s)
    assert calls and len(calls[0]) == 4    # one batched dispatch of 4
    assert outs == bases


def test_admit_many_fault_falls_back_to_single(eng):
    """A failing batched dispatch retries each member on the single-admit
    path — no request is lost or double-admitted."""
    prompts = [prompt(10, base=5 * i) for i in range(2)]
    bases = [run_one(eng, p, prefill_chunk=0, async_dispatch=False,
                     max_tokens=5)[0] for p in prompts]
    FAULTS.arm("engine.admit", "fail:once")
    try:
        sched = manual(Scheduler(eng, prefill_chunk=0,
                                 async_dispatch=False))
        try:
            reqs = [sched.submit(p, GREEDY, max_tokens=5)
                    for p in prompts]
            for _ in range(64):
                sched._step()
                if all(sched._running[s] is None
                       for s in range(eng.n_slots)) \
                        and sched._admission.empty():
                    break
            outs = [list(r.tokens()) for r in reqs]
        finally:
            sched.shutdown()
            for s in range(eng.n_slots):
                eng.release(s)
    finally:
        FAULTS.disarm("engine.admit")
    assert outs == bases


# ------------------------------------------------------------ semantics

def test_max_tokens_finishes_with_length(eng):
    toks, reason = run_one(eng, prompt(5), prefill_chunk=0,
                           async_dispatch=True, max_tokens=3)
    assert len(toks) == 3
    assert reason == "length"


def test_max_tokens_one_finishes_with_length(eng):
    # budget exhausted by the prefill-sampled token itself
    toks, reason = run_one(eng, prompt(5), prefill_chunk=0,
                           async_dispatch=False, max_tokens=1)
    assert len(toks) == 1
    assert reason == "length"


def test_dispatch_latency_gauges_populate(eng):
    assert set(eng.dispatch_ms) == {"decode", "admit", "extend", "spec"}
    run_one(eng, prompt(20), prefill_chunk=16, async_dispatch=True,
            max_tokens=4)
    assert eng.dispatch_ms["decode"] > 0.0
    assert eng.dispatch_ms["extend"] > 0.0


# ----------------------------------------------------------------- chaos

@pytest.mark.chaos
def test_restart_mid_async_pipeline_errors_once(eng, monkeypatch):
    """engine.step dies with a dispatch in flight: the already-computed
    dispatch is delivered, the owner gets exactly ONE error frame, the
    supervisor restarts, and the next request serves."""
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    sched = Scheduler(eng, prefill_chunk=0, async_dispatch=True,
                      restart_backoff=0.001)
    try:
        FAULTS.arm("engine.step", "fail:after=1")
        r = sched.submit(prompt(6), GREEDY, max_tokens=40)
        got = []
        with pytest.raises(RuntimeError):
            for chunk in r.chunks():
                got.extend(chunk)
        FAULTS.disarm("engine.step")
        # exactly once: nothing further lands on this request's queue
        deadline = time.monotonic() + 1.0
        while sched.n_restarts < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert r.out.empty()
        assert sched.n_restarts >= 1
        assert not sched.broken
        # the launch-before-materialise pipeline delivered dispatch N
        # before the failing launch of N+1 surfaced
        assert got == r.all_tokens[:len(got)]
        r2 = sched.submit(prompt(4), GREEDY, max_tokens=4)
        assert len(list(r2.tokens())) == 4
    finally:
        FAULTS.disarm("engine.step")
        sched.shutdown()
        for s in range(eng.n_slots):
            eng.release(s)


@pytest.mark.chaos
def test_restart_mid_chunked_prefill_errors_once(eng, monkeypatch):
    """engine.admit dies on an INTERLEAVED prefill piece (fail:after=1
    lets the first piece through): the supervisor restarts and the
    mid-prefill request errors exactly once."""
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    sched = Scheduler(eng, prefill_chunk=16, async_dispatch=False,
                      restart_backoff=0.001)
    try:
        c0 = METRICS.get("tpu_model_prefill_chunks_total")
        FAULTS.arm("engine.admit", "fail:after=1")
        r = sched.submit(prompt(40), GREEDY, max_tokens=4)
        with pytest.raises(RuntimeError):
            list(r.tokens())
        FAULTS.disarm("engine.admit")
        deadline = time.monotonic() + 1.0
        while sched.n_restarts < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert r.out.empty()
        assert sched.n_restarts >= 1
        assert METRICS.get("tpu_model_prefill_chunks_total") - c0 >= 1
        r2 = sched.submit(prompt(4), GREEDY, max_tokens=3)
        assert len(list(r2.tokens())) == 3
    finally:
        FAULTS.disarm("engine.admit")
        sched.shutdown()
        for s in range(eng.n_slots):
            eng.release(s)


@pytest.mark.chaos
def test_cancel_mid_chunked_prefill(eng):
    """Cancelling a request between prefill pieces frees the slot before
    any token was produced."""
    sched = manual(Scheduler(eng, prefill_chunk=16, async_dispatch=False))
    try:
        r = sched.submit(prompt(40), GREEDY, max_tokens=4)
        sched._step()              # first piece admitted, job registered
        assert sched._prefilling
        r.cancel()
        sched._step()
        assert not sched._prefilling
        assert r.out.get(timeout=1) == ("done", "cancelled")
        assert sched._running[r.slot or 0] is None
    finally:
        sched.shutdown()
        for s in range(eng.n_slots):
            eng.release(s)
