"""Chunked token fan-out and stream coalescing (ISSUE 1).

Covers the scheduler→service→HTTP streaming path introduced for the
serving-gap work: per-dispatch queue items, batched incremental
detokenisation, chunk-granular stop matching, and frame coalescing."""

import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.scheduler import RequestStats, Scheduler
from ollama_operator_tpu.runtime.service import StopMatcher
from ollama_operator_tpu.runtime import service as svc
from ollama_operator_tpu.server.app import (_StreamCoalescer,
                                            resolve_stream_flush,
                                            STREAM_FLUSH_TOKENS)
from ollama_operator_tpu.tokenizer import StreamDecoder

from test_tokenizer import spm_tok

GREEDY = SlotOptions(temperature=0.0, repeat_penalty=1.0)


def make_stack(slots=1, decode_chunk=8):
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    eng = Engine(cfg, params,
                 ecfg=EngineConfig(max_slots=slots, max_seq_len=64,
                                   decode_chunk=decode_chunk,
                                   cache_dtype=jnp.float32,
                                   min_prefill_bucket=16))
    return Scheduler(eng)


def byte_tok():
    byte_toks = [f"<0x{b:02X}>" for b in range(256)]
    return spm_tok(extra_tokens=byte_toks, extra_scores=[0.0] * 256)


def bids(t, text):
    return [t.vocab[f"<0x{b:02X}>"] for b in text.encode("utf-8")]


# --- scheduler: one queue item per decode dispatch ------------------------


def test_queue_items_bounded_by_decode_chunks():
    """ISSUE 1 acceptance: a request of N generated tokens crosses the
    scheduler→service queue in at most ceil(N / decode_chunk) items, not
    N items (per-token fan-out was ~35% of the old HTTP gap)."""
    sched = make_stack(slots=1, decode_chunk=8)
    try:
        r = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=17)
        chunks = list(r.chunks())
        total = sum(len(c) for c in chunks)
        assert total == 17
        assert len(chunks) <= math.ceil(17 / 8)
        # byte-for-byte identical token stream to the per-token view
        r2 = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=17)
        assert [t for c in chunks for t in c] == list(r2.tokens())
    finally:
        sched.shutdown()


# --- detokeniser: batched feed is equivalent to per-token feed ------------


def test_feed_many_matches_sequential_feed():
    t = byte_tok()
    ids = bids(t, "abéc世d")      # multi-byte chars inside
    for cut in range(1, len(ids)):
        sd1, sd2 = StreamDecoder(t), StreamDecoder(t)
        seq = "".join(sd1.feed(i) for i in ids)
        batched = sd2.feed_many(ids[:cut]) + sd2.feed_many(ids[cut:])
        assert seq + sd1.flush() == batched + sd2.flush() == "abéc世d"


def test_feed_many_holds_partial_utf8_at_chunk_boundary():
    t = byte_tok()
    ids = bids(t, "aé")               # é = 0xC3 0xA9
    sd = StreamDecoder(t)
    assert sd.feed_many(ids[:2]) == "a"    # 0xC3 held back
    assert sd.feed_many(ids[2:]) == "é"


# --- stop matching at chunk granularity -----------------------------------


def test_stop_matcher_split_across_chunks():
    sm = StopMatcher(["STOP"])
    assert sm.feed("hello ST") == "hello "   # partial match held back
    assert sm.feed("OP world") == ""
    assert sm.hit
    assert sm.flush() == ""


def test_stream_truncates_stop_split_across_chunks():
    """A stop string whose halves land in two different coalesced decode
    chunks must still truncate the stream and report done_reason="stop"."""
    t = byte_tok()
    chunks = [bids(t, "abcX"), bids(t, "Yz after stop")]

    class FakeReq:
        def __init__(self):
            self.cancelled = False
            self.stats = RequestStats(n_prompt=2)
            self.stats.n_generated = sum(len(c) for c in chunks)

        def chunks(self):
            for c in chunks:
                yield c

        def cancel(self):
            self.cancelled = True

    class FakeSelf:
        tokenizer = t

    req = FakeReq()
    out = list(svc.LoadedModel._stream(
        FakeSelf(), req, ["XY"], [1, 2], 100, time.monotonic(), None))
    pieces = [p for p, res in out if res is None]
    final = out[-1][1]
    assert "".join(pieces) == "abc"          # truncated before the stop
    assert final.text == "abc"
    assert final.done_reason == "stop"
    assert req.cancelled                     # slot freed on stop hit
    # _Piece carries per-chunk token counts for the HTTP coalescer
    assert sum(getattr(p, "n_tokens", 1) for p in pieces) == len(chunks[0])


# --- HTTP frame coalescing ------------------------------------------------


def test_resolve_stream_flush_precedence(monkeypatch):
    assert resolve_stream_flush(None) == (STREAM_FLUSH_TOKENS, 0.025)
    monkeypatch.setenv("TPU_STREAM_FLUSH_TOKENS", "4")
    monkeypatch.setenv("TPU_STREAM_FLUSH_MS", "100")
    assert resolve_stream_flush({}) == (4, 0.1)
    # request options win over env; floors apply
    assert resolve_stream_flush(
        {"stream_flush_tokens": 0, "stream_flush_ms": -5}) == (1, 0.0)
    assert resolve_stream_flush(
        {"stream_flush_tokens": "bogus"}) == (STREAM_FLUSH_TOKENS, 0.1)


def test_coalescer_first_piece_immediate_then_batches():
    frames = []
    co = _StreamCoalescer(frames.append, lambda s: s, max_tokens=4,
                          max_s=3600.0)
    co.add("a")                  # TTFT piece: flushes immediately
    assert frames == ["a"]
    co.add("b")
    co.add("c")
    assert frames == ["a"]       # below the token threshold, buffered
    co.add("defg")               # still 1 token by default attr... counts 1
    co.add("h")                  # 4th buffered token → flush
    assert frames == ["a", "bcdefgh"]
    co.add("tail")
    co.flush()                   # explicit end-of-stream drain
    assert frames == ["a", "bcdefgh", "tail"]
    assert co.frames == 3


def test_coalescer_respects_piece_token_counts():
    frames = []
    co = _StreamCoalescer(frames.append, lambda s: s, max_tokens=8,
                          max_s=3600.0)

    class P(str):
        n_tokens = 0
    first = P("x")
    first.n_tokens = 1
    co.add(first)                # flush (first frame)
    big = P("eight-token chunk")
    big.n_tokens = 8
    co.add(big)                  # 8 tokens at once → immediate flush
    assert frames == ["x", "eight-token chunk"]
