"""Go-template subset renderer against real ollama model templates."""

import pytest

from ollama_operator_tpu.server.template import Template, TemplateError

LLAMA2 = ("[INST] {{ if .System }}<<SYS>>{{ .System }}<</SYS>>\n\n"
          "{{ end }}{{ .Prompt }} [/INST]")
CHATML = ("{{ if .System }}<|im_start|>system\n{{ .System }}<|im_end|>\n"
          "{{ end }}{{ if .Prompt }}<|im_start|>user\n{{ .Prompt }}"
          "<|im_end|>\n{{ end }}<|im_start|>assistant\n")
MESSAGES = ("{{- range .Messages }}<|start|>{{ .Role }}\n"
            "{{ .Content }}<|end|>\n{{ end }}<|start|>assistant\n")


def test_llama2_with_system():
    out = Template(LLAMA2).render(system="be nice", prompt="hi")
    assert out == "[INST] <<SYS>>be nice<</SYS>>\n\nhi [/INST]"


def test_llama2_without_system():
    out = Template(LLAMA2).render(system="", prompt="hi")
    assert out == "[INST] hi [/INST]"


def test_chatml():
    out = Template(CHATML).render(system="sys", prompt="question")
    assert out == ("<|im_start|>system\nsys<|im_end|>\n"
                   "<|im_start|>user\nquestion<|im_end|>\n"
                   "<|im_start|>assistant\n")


def test_range_messages():
    msgs = [{"Role": "user", "Content": "a"},
            {"Role": "assistant", "Content": "b"}]
    out = Template(MESSAGES).render(messages=msgs)
    assert out == ("<|start|>user\na<|end|>\n<|start|>assistant\nb<|end|>\n"
                   "<|start|>assistant\n")


def test_eq_and_nested_if():
    tpl = Template('{{ range .Messages }}{{ if eq .Role "user" }}U:'
                   '{{ .Content }};{{ else }}A:{{ .Content }};{{ end }}'
                   '{{ end }}')
    out = tpl.render(messages=[{"Role": "user", "Content": "x"},
                               {"Role": "assistant", "Content": "y"}])
    assert out == "U:x;A:y;"


def test_trim_markers():
    tpl = Template("a\n{{- if true }}b{{ end }}  \n{{- .X }}")
    assert tpl.render(x="c") == "ab  \nc" or tpl.render(x="c") == "abc"


def test_lowercase_context_keys_work():
    assert Template("{{ .Prompt }}").render(prompt="p") == "p"


def test_unsupported_function_raises():
    with pytest.raises(TemplateError):
        Template('{{ slice .X 1 }}').render(x=[1, 2])


def test_else_if_chain():
    tpl = Template('{{ if .A }}a{{ else if .B }}b{{ else }}c{{ end }}')
    assert tpl.render(a=True, b=False) == "a"
    assert tpl.render(a=False, b=True) == "b"
    assert tpl.render(a=False, b=False) == "c"


def test_else_if_chain_three_deep():
    tpl = Template('{{ if eq .R "u" }}U{{ else if eq .R "a" }}A'
                   '{{ else if eq .R "s" }}S{{ else }}?{{ end }}')
    assert tpl.render(r="u") == "U"
    assert tpl.render(r="a") == "A"
    assert tpl.render(r="s") == "S"
    assert tpl.render(r="x") == "?"


def test_string_literal_and_ne():
    tpl = Template('{{ if ne .A "z" }}ok{{ end }}')
    assert tpl.render(a="q") == "ok"
    assert tpl.render(a="z") == ""
