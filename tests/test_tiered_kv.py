"""Tiered KV cache: host-RAM spill, async restitch, fleet snapshots.

ISSUE 18 coverage: HostArena byte accounting and the break-even model,
spill-on-evict only taking epoch-quiescent pages, restitched streams
bit-identical to recomputed ones (greedy AND seeded, engine-level AND
through the async/sync scheduler, cross-checked against a dense
engine), LRU host-entry drop under arena pressure, probe tier
transitions, the tier-2 export/import snapshot round-trip (plus the
gguf/store persistence), supervised restart dropping tier-1 cleanly,
and the pages.{spill,restitch} chaos drills (a failed spill is a plain
eviction; a failed restitch is a clean cold fallback with no leaks).
"""

import dataclasses
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.models.config import PRESETS
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.host_cache import (HostArena,
                                                    host_cache_bytes,
                                                    worth_restitch)
from ollama_operator_tpu.runtime.scheduler import Scheduler
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

BASE = PRESETS["tiny"]
XLA = dataclasses.replace(BASE, kernels="xla")
GREEDY = SlotOptions(temperature=0.0)
SEEDED = SlotOptions(temperature=0.9, top_k=40)
DENSE = EngineConfig(max_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
                     min_prefill_bucket=16)
PAGED = dataclasses.replace(DENSE, paged=True, page_size=8)

PREFIX = np.arange(1, 25, dtype=np.int32)          # 24 tokens = 3 pages
FULL = np.concatenate([PREFIX, np.array([70, 71, 72], np.int32)])
DONOR = np.concatenate([PREFIX, np.array([60, 61], np.int32)])
PROMPT = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(BASE, jax.random.key(0), jnp.float32)


@pytest.fixture()
def arena_env(monkeypatch):
    """Tier-1 arena on (~32 tiny-preset pages) for engines built after."""
    monkeypatch.setenv("TPU_HOST_CACHE_GB", "0.001")


def _gen(eng, slot, full, opts, n):
    """Cold admission + n decode steps on one slot (slot left active)."""
    first = eng.admit(slot, np.asarray(full, np.int32), opts)
    return [first] + [int(eng.decode()[slot]) for _ in range(n)]


def _drain(sched, deadline_s=5.0):
    t1 = time.monotonic() + deadline_s
    while ((sched.n_active or sched.engine.quarantined_pages)
           and time.monotonic() < t1):
        time.sleep(0.01)
    assert sched.n_active == 0
    assert sched.engine.quarantined_pages == 0


def _seed_spilled_prefix(eng):
    """Donate the 3-page PREFIX, then spill all of it to the host tier."""
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    assert eng.radix_pages == 3
    assert eng.radix_evict(10) >= 3
    assert eng.radix_pages == 0 and eng.radix_hosted == 3
    assert eng.host_cache_pages == 3


# ---------------------------------------------------------------------------
# host accounting units (no engine)
# ---------------------------------------------------------------------------

def test_host_cache_bytes_parsing():
    assert host_cache_bytes("0.5") == 1 << 29
    assert host_cache_bytes("2") == 2 << 30
    assert host_cache_bytes("0") == 0
    assert host_cache_bytes("") == 0
    assert host_cache_bytes("-1") == 0
    assert host_cache_bytes("junk") == 0


def test_host_arena_accounting():
    page = ({"k": np.zeros((2, 8), np.float32)},
            {"v": np.zeros((2, 8), np.float32)})   # 128 bytes
    arena = HostArena(capacity_bytes=300, page_bytes=128)
    assert arena.room_for(2) and not arena.room_for(3)
    e1 = arena.store(page)
    assert e1.nbytes == 128 and arena.used_bytes == 128
    assert arena.n_entries == 1
    e2 = arena.store(page, snapshot=True)
    assert e2.snapshot and not e1.snapshot
    assert not arena.room_for(1)                   # 256 + 128 > 300
    arena.free(e1)
    assert arena.used_bytes == 128 and e1.kv is None
    arena.free(None)                               # tolerated no-op
    arena.free_all([e2, None])
    assert arena.used_bytes == 0 and arena.n_entries == 0
    e3 = arena.store(page)
    arena.clear()                                  # O(1) reset path
    assert arena.used_bytes == 0 and arena.n_entries == 0
    assert e3.kv is not None                       # entries die with nodes


def test_worth_restitch_floor_and_cpu_default(monkeypatch):
    monkeypatch.setenv("TPU_HOST_CACHE_BREAK_EVEN", "32")
    assert worth_restitch(BASE, 0, 32, 10 ** 12)   # floor met: bytes moot
    assert not worth_restitch(BASE, 0, 31, 1)
    monkeypatch.delenv("TPU_HOST_CACHE_BREAK_EVEN")
    # CPU mesh: no detectable peak -> the copy always beats recompute
    assert worth_restitch(BASE, 0, 8, 1 << 30)
    assert not worth_restitch(BASE, 0, 0, 0)       # empty run never uploads


def test_arena_disabled_without_knob(params):
    eng = Engine(XLA, params, ecfg=PAGED)
    assert not eng.host_cache_enabled
    assert eng.host_cache_pages == 0
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    sp0 = eng.n_spilled_pages
    assert eng.radix_evict(10) >= 3                # classic tierless evict
    assert eng.n_spilled_pages == sp0 and eng.radix_hosted == 0


# ---------------------------------------------------------------------------
# engine: spill -> restitch parity (greedy + seeded, vs dense reference)
# ---------------------------------------------------------------------------

def test_spill_restitch_stream_parity(params, arena_env):
    """Restitched streams must be bit-identical to recomputed ones —
    greedy and derived-seed sampling — and the paged tiered engine must
    match a dense (non-paged, cache-free) engine on the same prompt."""
    eng = Engine(XLA, params, ecfg=PAGED)
    dense = Engine(XLA, params, ecfg=DENSE)
    assert eng.host_cache_enabled and eng.host_page_bytes > 0
    cold = {}
    for key, opts in (("g", GREEDY), ("s", SEEDED)):
        cold[key] = _gen(eng, 0, FULL, opts, 3)
        eng.release(0)                             # no donation: stays cold
        ref = _gen(dense, 0, FULL, opts, 3)
        dense.release(0)
        assert cold[key] == ref, f"paged-vs-dense cold drift ({key})"
    assert eng.radix_nodes == 0
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    for key, opts in (("g", GREEDY), ("s", SEEDED)):
        sp0 = eng.n_spilled_pages
        m0 = METRICS.get("tpu_model_spilled_pages_total")
        assert eng.radix_evict(10) >= 3            # quiescent: all spill
        assert eng.n_spilled_pages - sp0 == 3
        assert METRICS.get("tpu_model_spilled_pages_total") - m0 == 3
        assert eng.radix_pages == 0 and eng.host_cache_pages == 3
        assert eng.host_cache_used_bytes == 3 * eng.host_page_bytes
        want, tier = eng.prefix_probe_tier(FULL)
        assert want >= 24 and tier == 1
        got = eng.stitch(0, FULL, want)
        assert got >= 24
        ls = eng.last_stitch
        assert ls["t1"] >= 24 and ls["skip1"] == 0 and ls["t2"] == 0
        first = eng.extend(0, FULL, got, opts)
        out = [first] + [int(eng.decode()[0]) for _ in range(3)]
        assert out == cold[key], f"restitched stream drift ({key})"
        eng.release(0)
        # the run was promoted back: pure-HBM path, arena drained
        want, tier = eng.prefix_probe_tier(FULL)
        assert tier == 0 and want >= 24
        assert eng.host_cache_pages == 0 and eng.radix_pages == 3
    eng._pt.check()


def test_spill_requires_quiescent_pool(params, arena_env):
    """Eviction with a decode in flight must NOT spill (the gather would
    race the launched program): pages are plainly freed through the
    epoch quarantine, and the same eviction after the fence spills."""
    eng = Engine(XLA, params, ecfg=PAGED)
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    _gen(eng, 1, PROMPT, GREEDY, 1)
    handle = eng.decode_n_launch(2)                # epoch opens, unretired
    assert not eng._pt.quiescent
    sp0 = eng.n_spilled_pages
    assert eng.radix_evict(10) >= 3                # frees, must not spill
    assert eng.n_spilled_pages == sp0
    assert eng.host_cache_pages == 0 and eng.radix_nodes == 0
    handle.wait()
    eng.fence_quiesce()
    eng.release(1)
    assert eng._pt.quiescent
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    assert eng.radix_evict(10) >= 3                # fenced: now it spills
    assert eng.n_spilled_pages - sp0 == 3
    assert eng.host_cache_pages == 3
    eng.radix_reset()
    eng._pt.check()


def test_host_lru_drop_under_arena_pressure(params, arena_env):
    """An arena narrower than the spill set drops least-recently-used
    tier-1 entries to admit new spills — occupancy never exceeds
    capacity and the byte accounting stays exact."""
    eng = Engine(XLA, params, ecfg=PAGED)
    # shrink the arena to 2.5 pages (env gave a generous one)
    eng._arena = HostArena(int(2.5 * eng.host_page_bytes),
                           eng.host_page_bytes)
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    assert eng.radix_evict(10) >= 3
    assert eng.host_cache_pages == 2               # LRU leaf made room
    assert eng.radix_hosted == 2 and eng.radix_pages == 0
    assert eng.host_cache_used_bytes <= eng.host_cache_capacity_bytes
    # the surviving 16-token run still restitches and serves
    want, tier = eng.prefix_probe_tier(FULL)
    assert want == 16 and tier == 1
    got = eng.stitch(0, FULL, want)
    assert got == 16
    eng.release(0)
    eng._pt.check()


def test_break_even_floor_skips_short_runs(params, arena_env, monkeypatch):
    """A flat TPU_HOST_CACHE_BREAK_EVEN floor above the run length makes
    the stitch recompute instead: the run stays spilled, skips are
    counted by provenance, and the recomputed stream is identical."""
    monkeypatch.setenv("TPU_HOST_CACHE_BREAK_EVEN", "1000")
    eng = Engine(XLA, params, ecfg=PAGED)
    cold = _gen(eng, 0, FULL, GREEDY, 3)
    eng.release(0)
    _seed_spilled_prefix(eng)
    want, tier = eng.prefix_probe_tier(FULL)
    assert want >= 24 and tier == 1
    assert eng.stitch(0, FULL, want) == 0          # whole run under floor
    ls = eng.last_stitch
    assert ls["skip1"] == 24 and ls["t1"] == 0
    assert eng.radix_hosted == 3                   # run stays spilled
    out = _gen(eng, 0, FULL, GREEDY, 3)            # clean cold recompute
    assert out == cold
    eng.release(0)
    eng._pt.check()


# ---------------------------------------------------------------------------
# scheduler: async/sync restitch parity + tier metrics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("overlap", [True, False], ids=["async", "sync"])
def test_scheduler_restitch_parity(params, arena_env, overlap):
    """Through the real scheduler (double-buffered AND forced-sync): a
    spilled prefix restitches transparently, the stream is bit-identical
    to the cold one, and the tier-1 hit tokens land in the metrics."""
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng, async_dispatch=overlap)
    try:
        out1 = list(sched.submit(FULL, max_tokens=4, opts=GREEDY).tokens())
        _drain(sched)
        assert eng.radix_pages == 3                # donated on finish
        eng.fence_quiesce()                        # retire the last epoch
        assert eng.radix_evict(10) >= 3
        assert eng.host_cache_pages == 3
        h0 = METRICS.get("tpu_model_tier_hit_tokens_total", '{tier="1"}')
        fb0 = METRICS.get("tpu_model_async_fallback_total")
        r2 = sched.submit(FULL, max_tokens=4, opts=GREEDY)
        out2 = list(r2.tokens())
        assert r2.error is None and out2 == out1
        assert r2.stats.n_reused >= 24
        _drain(sched)
        assert (METRICS.get("tpu_model_tier_hit_tokens_total",
                            '{tier="1"}') - h0) >= 24
        # restitch never forces the dispatch loop out of double-buffering
        assert METRICS.get("tpu_model_async_fallback_total") == fb0
        assert eng.free_pages == eng._pt.data_pages - eng.radix_pages
        eng._pt.check()
    finally:
        sched.shutdown()


@pytest.mark.chaos
def test_restart_drops_host_tier_cleanly(params, arena_env, monkeypatch):
    """A supervised engine restart rebuilds device state, so the host
    tier must die with the tree: no arena residue, no pinned pages, and
    serving re-populates both tiers afterwards."""
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng, restart_backoff=0.001)
    try:
        r1 = sched.submit(FULL, max_tokens=4, opts=GREEDY)
        assert len(list(r1.tokens())) == 4
        _drain(sched)
        eng.fence_quiesce()
        assert eng.radix_evict(10) >= 3
        assert eng.host_cache_pages == 3
        FAULTS.arm("engine.step", "fail:once")
        r2 = sched.submit(PROMPT, max_tokens=4, opts=GREEDY)
        with pytest.raises(RuntimeError):
            list(r2.tokens())
        t1 = time.monotonic() + 5
        while sched.n_restarts < 1 and time.monotonic() < t1:
            time.sleep(0.01)
        assert sched.n_restarts >= 1 and not sched.broken
        assert eng.radix_nodes == 0 and eng.radix_hosted == 0
        assert eng.host_cache_pages == 0
        assert eng.host_cache_used_bytes == 0
        assert eng.free_pages == eng._pt.data_pages
        r3 = sched.submit(FULL, max_tokens=4, opts=GREEDY)
        assert len(list(r3.tokens())) == 4
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# chaos: pages.spill / pages.restitch
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_pages_spill_fault_is_a_plain_eviction(params, arena_env):
    """An armed pages.spill fault must degrade a spill to the tierless
    eviction path: the page is freed, nothing lands in the arena, and
    the next (disarmed) eviction spills normally."""
    eng = Engine(XLA, params, ecfg=PAGED)
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    FAULTS.arm("pages.spill", "fail:once")
    free0 = eng.free_pages
    assert eng.radix_evict(1) == 1                 # freed, not spilled
    assert eng.free_pages == free0 + 1
    assert eng.host_cache_pages == 0 and eng.n_spilled_pages == 0
    assert eng.radix_evict(1) == 1                 # disarmed: spills
    assert eng.host_cache_pages == 1 and eng.n_spilled_pages == 1
    eng.radix_reset()
    eng._pt.check()


@pytest.mark.chaos
def test_pages_restitch_fault_falls_back_cold(params, arena_env):
    """CI chaos drill: a restitch failing mid-stitch must fall back to a
    clean cold prefill — bit-identical stream, zero reuse reported, no
    leaked pages, page-table check() clean."""
    eng = Engine(XLA, params, ecfg=PAGED)
    sched = Scheduler(eng)
    try:
        out1 = list(sched.submit(FULL, max_tokens=4, opts=GREEDY).tokens())
        _drain(sched)
        eng.fence_quiesce()
        assert eng.radix_evict(10) >= 3            # spill the donated run
        assert eng.host_cache_pages == 3
        FAULTS.arm("pages.restitch", "fail:once")
        r2 = sched.submit(FULL, max_tokens=4, opts=GREEDY)
        out2 = list(r2.tokens())
        assert r2.error is None
        assert out2 == out1                        # cold fallback stream
        assert r2.stats.n_reused == 0              # it really went cold
        _drain(sched)
        assert eng.free_pages == eng._pt.data_pages - eng.radix_pages
        eng._pt.check()
        # recovery: the next hit restitches for real
        r3 = sched.submit(FULL, max_tokens=4, opts=GREEDY)
        assert list(r3.tokens()) == out1
        assert r3.stats.n_reused >= 16
    finally:
        sched.shutdown()


# ---------------------------------------------------------------------------
# tier 2: fleet prefix snapshots
# ---------------------------------------------------------------------------

def test_prefix_snapshot_round_trip(params, arena_env, tmp_path):
    """export_prefixes -> gguf/store persistence -> import_prefixes into
    a fresh engine: nodes arrive as tier-2 host entries, the probe says
    tier 2, the stitched stream is bit-identical, and a geometry
    mismatch or re-import is refused without side effects."""
    from ollama_operator_tpu.gguf import store as gstore
    eng = Engine(XLA, params, ecfg=PAGED)
    cold = _gen(eng, 0, FULL, GREEDY, 3)
    eng.release(0)
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    blob = eng.export_prefixes()
    assert blob is not None
    gstore.save_prefix_snapshot(str(tmp_path), "k1", blob)
    assert gstore.load_prefix_snapshot(str(tmp_path), "missing") is None
    blob = gstore.load_prefix_snapshot(str(tmp_path), "k1")
    fresh = Engine(XLA, params, ecfg=PAGED)
    assert fresh.import_prefixes(blob) == 3
    assert fresh.radix_hosted == 3 and fresh.radix_pages == 0
    assert fresh.host_cache_pages == 3
    assert fresh.import_prefixes(blob) == 0        # idempotent re-import
    want, tier = fresh.prefix_probe_tier(FULL)
    assert want >= 24 and tier == 2
    h2 = METRICS.get("tpu_model_tier_hit_tokens_total", '{tier="2"}')
    got = fresh.stitch(0, FULL, want)
    assert got >= 24
    ls = fresh.last_stitch
    assert ls["t2"] >= 24 and ls["t1"] == 0        # snapshot provenance
    first = fresh.extend(0, FULL, got, GREEDY)
    out = [first] + [int(fresh.decode()[0]) for _ in range(3)]
    assert out == cold                             # warm replica parity
    fresh.release(0)
    assert METRICS.get("tpu_model_tier_hit_tokens_total",
                       '{tier="2"}') == h2         # engine-level: no attrib
    # geometry guard: a snapshot from a different page size is refused
    data = pickle.loads(blob)
    data["ps"] = 16
    assert fresh.import_prefixes(pickle.dumps(data)) == 0
    assert fresh.import_prefixes(b"corrupt") == 0
    fresh._pt.check()
    eng._pt.check()


def test_snapshot_export_respects_byte_budget(params, arena_env):
    """The export budget is honoured greedily MRU-first: a budget below
    one page yields no snapshot, a one-page budget ships exactly the
    root chunk (children only ship when their parent made the cut)."""
    eng = Engine(XLA, params, ecfg=PAGED)
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    assert eng.export_prefixes(max_bytes=1) is None
    one = eng.export_prefixes(max_bytes=eng.host_page_bytes + 4096)
    assert one is not None
    fresh = Engine(XLA, params, ecfg=PAGED)
    assert fresh.import_prefixes(one) == 1         # rooted single chunk
    want, tier = fresh.prefix_probe_tier(FULL)
    assert want == 8 and tier == 2
    fresh._pt.check()


def test_scheduler_attributes_tier2_hits(params, arena_env):
    """A just-woken replica's first shared-prefix request through the
    scheduler must be a warm tier-2 hit in the metrics matrix."""
    eng = Engine(XLA, params, ecfg=PAGED)
    toks = _gen(eng, 0, DONOR, GREEDY, 2)
    eng.donate_prefix(0, list(DONOR) + toks[:-1])
    blob = eng.export_prefixes()
    fresh = Engine(XLA, params, ecfg=PAGED)
    assert fresh.import_prefixes(blob) == 3
    sched = Scheduler(fresh)
    try:
        h2 = METRICS.get("tpu_model_tier_hit_tokens_total", '{tier="2"}')
        r = sched.submit(FULL, max_tokens=4, opts=GREEDY)
        assert len(list(r.tokens())) == 4 and r.error is None
        assert r.stats.n_reused >= 24              # warm first request
        _drain(sched)
        assert (METRICS.get("tpu_model_tier_hit_tokens_total",
                            '{tier="2"}') - h2) >= 24
    finally:
        sched.shutdown()
