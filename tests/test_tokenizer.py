"""Tokenizer tests over synthetic SPM and GPT-2 vocabularies."""

from ollama_operator_tpu.tokenizer import Tokenizer, StreamDecoder
from ollama_operator_tpu.tokenizer.tokenizer import (
    TT_BYTE, TT_CONTROL, TT_NORMAL, _BYTE_ENC)


def spm_tok(extra_tokens=(), extra_scores=(), **kw):
    tokens = ["<unk>", "<s>", "</s>", "▁", "a", "b", "c", "ab", "▁a", "bc"]
    scores = [0.0, 0.0, 0.0, -1.0, -2.0, -2.0, -2.0, -0.5, -0.4, -0.6]
    types = [2, 3, 3] + [TT_NORMAL] * 7
    tokens += list(extra_tokens)
    scores += list(extra_scores)
    types += [TT_BYTE] * len(extra_tokens)
    return Tokenizer("llama", tokens, scores, types, bos_id=1, eos_id=2, **kw)


def test_spm_basic_merge():
    t = spm_tok()
    # " a bc" → ▁a ▁ b c → merges: "▁a"(-0.4), "bc"(-0.6)
    ids = t.encode("a bc")
    assert ids[0] == 1  # bos
    assert ids[1:] == [t.vocab["▁a"], t.vocab["▁"], t.vocab["bc"]]


def test_spm_merge_order_prefers_higher_score():
    t = spm_tok()
    # "ab" alone (after prefix "▁ab"): ▁,a,b → "▁a" (-0.4) beats "ab" (-0.5)
    ids = t.encode("ab")
    assert ids[1:] == [t.vocab["▁a"], t.vocab["b"]]


def test_spm_byte_fallback():
    byte_toks = [f"<0x{b:02X}>" for b in range(256)]
    t = spm_tok(extra_tokens=byte_toks, extra_scores=[0.0] * 256)
    ids = t.encode("é", add_bos=False)  # é = 0xC3 0xA9, not in vocab
    assert [t.tokens[i] for i in ids[-2:]] == ["<0xC3>", "<0xA9>"]
    assert t.decode(ids) == " é"  # add_space_prefix


def test_spm_decode_roundtrip():
    t = spm_tok()
    ids = t.encode("a bc ab", add_bos=False)
    assert t.decode(ids) == " a bc ab"


def test_special_token_parsing():
    tokens = ["<unk>", "<s>", "</s>", "▁", "h", "i", "<|eot|>"]
    scores = [0.0] * 7
    types = [2, 3, 3, 1, 1, 1, TT_CONTROL]
    t = Tokenizer("llama", tokens, scores, types, bos_id=1, eos_id=2)
    ids = t.encode("hi<|eot|>", add_bos=False)
    assert ids[-1] == 6
    assert 6 not in t.encode("hi<|eot|>", add_bos=False,
                             parse_special=False)


def gpt2_tok():
    # byte-level pieces for h,e,l,o + merges up to "hello"
    base = [_BYTE_ENC[ord(c)] for c in "helo "]
    pieces = base + ["he", "ll", "hell", "hello", "<|end|>"]
    merges = [f"{_BYTE_ENC[ord('h')]} {_BYTE_ENC[ord('e')]}",
              f"{_BYTE_ENC[ord('l')]} {_BYTE_ENC[ord('l')]}",
              "he ll", "hell " + _BYTE_ENC[ord('o')]]
    types = [TT_NORMAL] * (len(pieces) - 1) + [TT_CONTROL]
    return Tokenizer("gpt2", pieces, None, types, merges=merges,
                     bos_id=-1, eos_id=len(pieces) - 1, add_bos=False)


def test_gpt2_bpe_merges():
    t = gpt2_tok()
    ids = t.encode("hello")
    assert [t.tokens[i] for i in ids] == ["hello"]
    assert t.decode(ids) == "hello"


def test_gpt2_partial_merge_and_unknown_bytes():
    t = gpt2_tok()
    ids = t.encode("hell")
    assert [t.tokens[i] for i in ids] == ["hell"]
    ids2 = t.encode("ho")  # no merge for "ho"
    assert len(ids2) == 2
    assert t.decode(ids2) == "ho"


def test_stream_decoder_utf8_boundary():
    byte_toks = [f"<0x{b:02X}>" for b in range(256)]
    t = spm_tok(extra_tokens=byte_toks, extra_scores=[0.0] * 256)
    sd = StreamDecoder(t)
    id_c3 = t.vocab["<0xC3>"]
    id_a9 = t.vocab["<0xA9>"]
    assert sd.feed(id_c3) == ""       # incomplete utf-8 held back
    assert sd.feed(id_a9) == "é"
    assert sd.feed(t.vocab["a"]) == "a"
    assert sd.flush() == ""


def test_eog_detection():
    t = spm_tok()
    assert t.is_eog(2)
    assert not t.is_eog(4)


def test_from_gguf_metadata():
    md = {
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": ["<unk>", "<s>", "</s>", "▁", "x"],
        "tokenizer.ggml.scores": [0.0, 0.0, 0.0, -1.0, -2.0],
        "tokenizer.ggml.token_type": [2, 3, 3, 1, 1],
        "tokenizer.ggml.bos_token_id": 1,
        "tokenizer.ggml.eos_token_id": 2,
        "tokenizer.ggml.add_bos_token": True,
    }
    t = Tokenizer.from_gguf_metadata(md)
    assert t.bos_id == 1 and t.eos_id == 2 and t.n_vocab == 5
    assert t.encode("x")[0] == 1
