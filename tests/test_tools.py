"""Tool calling: template rendering of .Tools/.ToolCalls, model-output
parsing into structured tool_calls, and the chat-surface contract."""

import json

import pytest

from ollama_operator_tpu.server.template import Template
from ollama_operator_tpu.server.tools import (parse_tool_calls,
                                              to_template_tool_calls,
                                              to_template_tools)

WEATHER = {
    "type": "function",
    "function": {
        "name": "get_weather",
        "description": "Get the current weather",
        "parameters": {
            "type": "object",
            "properties": {"city": {"type": "string"}},
            "required": ["city"],
        },
    },
}


# --- parsing -----------------------------------------------------------------

def test_parse_bare_object():
    out = parse_tool_calls('{"name": "get_weather", "arguments": '
                           '{"city": "Oslo"}}')
    assert out == [{"function": {"name": "get_weather",
                                 "arguments": {"city": "Oslo"}}}]


def test_parse_parameters_alias_and_list():
    out = parse_tool_calls('[{"name": "a", "parameters": {"x": 1}}, '
                           '{"name": "b", "arguments": {}}]')
    assert [c["function"]["name"] for c in out] == ["a", "b"]
    assert out[0]["function"]["arguments"] == {"x": 1}


def test_parse_embedded_after_prose():
    text = ('Sure, let me check that.\n'
            '{"name": "get_weather", "arguments": {"city": "Bergen"}}')
    out = parse_tool_calls(text)
    assert out[0]["function"]["arguments"] == {"city": "Bergen"}


def test_parse_rejects_non_tool_output():
    assert parse_tool_calls("The weather is nice today.") == []
    assert parse_tool_calls('{"city": "Oslo"}') == []          # no name
    assert parse_tool_calls('{"name": "x"}') == []             # no args
    assert parse_tool_calls('{"name": "", "arguments": {}}') == []
    assert parse_tool_calls("") == []


# --- template shapes ---------------------------------------------------------

def test_to_template_tools_shape():
    """Lowercase wire keys — the template's capitalized field access
    (.Function.Name) resolves via the engine's lowercase fallback, and
    json-emission produces model-facing wire JSON."""
    [t] = to_template_tools([WEATHER])
    assert t["type"] == "function"
    assert t["function"]["name"] == "get_weather"
    assert t["function"]["parameters"]["required"] == ["city"]


def test_to_template_tool_calls_parses_string_arguments():
    [c] = to_template_tool_calls(
        [{"function": {"name": "f", "arguments": '{"x": 2}'}}])
    assert c["function"]["arguments"] == {"x": 2}


TOOL_TPL = (
    "{{ if .Tools }}Tools:\n"
    "{{ range .Tools }}{{ json .Function }}\n{{ end }}{{ end }}"
    "{{ range .Messages }}[{{ .Role }}] {{ .Content }}"
    "{{ if .ToolCalls }}{{ range .ToolCalls }}"
    "<call {{ .Function.Name }} {{ .Function.Arguments }}>"
    "{{ end }}{{ end }}\n{{ end }}"
)


def test_template_renders_tools_and_calls():
    tpl = Template(TOOL_TPL)
    out = tpl.render(
        tools=to_template_tools([WEATHER]),
        messages=[
            {"Role": "user", "Content": "weather in Oslo?"},
            {"Role": "assistant", "Content": "",
             "ToolCalls": to_template_tool_calls(
                 [{"function": {"name": "get_weather",
                                "arguments": {"city": "Oslo"}}}])},
            {"Role": "tool", "Content": "12C, sunny"},
        ])
    assert '"name": "get_weather"' in out
    assert '"required": ["city"]' in out         # schema JSON-emitted
    assert '<call get_weather {"city": "Oslo"}>' in out
    assert "[tool] 12C, sunny" in out


def test_template_json_function():
    tpl = Template('{{ json . }}')
    assert tpl.render(**{}) or True  # render of empty dot
    tpl = Template('{{ json .X }}')
    assert tpl.render(x=[1, 2]) == "[1, 2]"


def test_render_chat_rejects_tools_without_template_support():
    """A model whose template has no .Tools section can't honour tools."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ollama_operator_tpu.models import config as cfglib
    from ollama_operator_tpu.models import decoder
    from ollama_operator_tpu.runtime.engine import EngineConfig
    from ollama_operator_tpu.runtime.service import LoadedModel
    from ollama_operator_tpu.tokenizer import Tokenizer

    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    tok = Tokenizer(model="llama",
                    tokens=[f"t{i}" for i in range(cfg.vocab_size)])
    lm = LoadedModel("tiny", cfg, params, tok,
                     template="{{ .System }}|{{ .Prompt }}",
                     ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                       cache_dtype=jnp.float32,
                                       min_prefill_bucket=16))
    try:
        with pytest.raises(ValueError, match="does not support tools"):
            lm.render_chat([{"role": "user", "content": "hi"}],
                           tools=[WEATHER])
        # and with a tools-aware template the same call renders
        out = lm.render_chat([{"role": "user", "content": "hi"}],
                             template=TOOL_TPL, tools=[WEATHER])
        assert "get_weather" in out
    finally:
        lm.unload()


def test_parse_multiple_separate_objects():
    """Parallel calls emitted as separate JSON objects all survive."""
    text = ('{"name": "f", "arguments": {}} and also '
            '{"name": "g", "arguments": {"x": 1}}')
    out = parse_tool_calls(text)
    assert [c["function"]["name"] for c in out] == ["f", "g"]


def test_split_keeps_prose_content():
    from ollama_operator_tpu.server.tools import split_tool_calls
    calls, prose = split_tool_calls(
        'Sure, let me check.\n'
        '{"name": "get_weather", "arguments": {"city": "Bergen"}}\nDone.')
    assert calls[0]["function"]["name"] == "get_weather"
    assert "Sure, let me check." in prose and "Done." in prose
    # ordinary JSON that is NOT an invocation stays in the prose
    calls, prose = split_tool_calls('The answer is {"city": "Oslo"}.')
    assert calls == [] and '{"city": "Oslo"}' in prose
