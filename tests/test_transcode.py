"""GGUF→params transcoding: name mapping, transposes, rope-layout fix,
store cache round trip, and end-to-end logits equivalence."""

import jax
import jax.numpy as jnp
import numpy as np

from ollama_operator_tpu.gguf import transcode as TC
from ollama_operator_tpu.gguf import writer as W
from ollama_operator_tpu.gguf.reader import GGUFFile
from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.ops.rope import apply_rope, rope_angles

rng = np.random.default_rng(7)


def permute_to_interleaved(w_out_in: np.ndarray, n_heads: int) -> np.ndarray:
    """Inverse of transcode._unpermute_rope (HF→Meta style permute)."""
    out, inn = w_out_in.shape
    hd = out // n_heads
    return (w_out_in.reshape(n_heads, 2, hd // 2, inn)
            .transpose(0, 2, 1, 3).reshape(out, inn))


def interleaved_rope(x: np.ndarray, positions: np.ndarray,
                     theta: float) -> np.ndarray:
    """Reference rope in the Meta/llama.cpp 'NORM' convention: rotation i
    acts on channel pair (2i, 2i+1). x [T, H, hd]."""
    T, H, hd = x.shape
    half = hd // 2
    inv = 1.0 / (theta ** (np.arange(half) / half))
    ang = positions[:, None] * inv  # [T, half]
    cos, sin = np.cos(ang), np.sin(ang)
    out = x.copy()
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    out[..., 0::2] = x1 * cos[:, None, :] - x2 * sin[:, None, :]
    out[..., 1::2] = x2 * cos[:, None, :] + x1 * sin[:, None, :]
    return out


def test_rope_unpermute_preserves_attention_scores():
    """half-split rope on unpermuted weights ≡ interleaved rope on original
    weights, as far as attention scores are concerned."""
    D, H, hd, T = 32, 4, 16, 5
    theta = 10000.0
    wq_gguf = rng.standard_normal((H * hd, D)).astype(np.float32)
    wk_gguf = rng.standard_normal((H * hd, D)).astype(np.float32)
    x = rng.standard_normal((T, D)).astype(np.float32)
    pos = np.arange(T).astype(np.float32)

    # reference path (llama.cpp semantics)
    q_ref = (x @ wq_gguf.T).reshape(T, H, hd)
    k_ref = (x @ wk_gguf.T).reshape(T, H, hd)
    q_ref = interleaved_rope(q_ref, pos, theta)
    k_ref = interleaved_rope(k_ref, pos, theta)
    scores_ref = np.einsum("thd,shd->hts", q_ref, k_ref)

    # our path
    wq = TC._unpermute_rope(wq_gguf, H).T
    wk = TC._unpermute_rope(wk_gguf, H).T
    q = (x @ wq).reshape(1, T, H, hd)
    k = (x @ wk).reshape(1, T, H, hd)
    cos, sin = rope_angles(jnp.asarray(pos[None]), hd, theta)
    q2 = np.asarray(apply_rope(jnp.asarray(q), cos, sin, hd))[0]
    k2 = np.asarray(apply_rope(jnp.asarray(k), cos, sin, hd))[0]
    scores = np.einsum("thd,shd->hts", q2, k2)

    np.testing.assert_allclose(scores, scores_ref, rtol=1e-4, atol=1e-4)


def write_tiny_llama_gguf(path: str, cfg, params, moe_merged=None,
                          tokens=None, token_types=None, eos_id=None):
    """Export decoder params as a llama.cpp-convention GGUF (transposed,
    q/k re-permuted to the interleaved layout). For MoE configs pass
    moe_merged=True (merged ffn_*_exps tensors) or False (legacy
    per-expert split tensors). ``tokens``/``token_types``/``eos_id``
    override the default placeholder vocab (e.g. a JSON-capable vocab for
    format-constrained tests)."""
    w = W.GGUFWriter(path)
    w.add_meta("general.architecture", "llama")
    w.add_meta("llama.block_count", cfg.n_layers)
    w.add_meta("llama.embedding_length", cfg.dim)
    w.add_meta("llama.attention.head_count", cfg.n_heads)
    w.add_meta("llama.attention.head_count_kv", cfg.n_kv_heads)
    w.add_meta("llama.attention.key_length", cfg.head_dim)
    w.add_meta("llama.feed_forward_length", cfg.ffn_dim)
    w.add_meta("llama.context_length", cfg.max_seq_len)
    w.add_meta("llama.rope.freq_base", cfg.rope_theta)
    w.add_meta("llama.attention.layer_norm_rms_epsilon", cfg.norm_eps)
    if cfg.n_experts:
        w.add_meta("llama.expert_count", cfg.n_experts)
        w.add_meta("llama.expert_used_count", cfg.n_experts_used)
    toks = tokens or [f"t{i}" for i in range(cfg.vocab_size)]
    assert len(toks) == cfg.vocab_size
    w.add_meta("tokenizer.ggml.model", "llama")
    w.add_meta("tokenizer.ggml.tokens", toks)
    w.add_meta("tokenizer.ggml.scores", [0.0] * cfg.vocab_size)
    w.add_meta("tokenizer.ggml.token_type",
               token_types or [1] * cfg.vocab_size)
    if eos_id is not None:
        w.add_meta("tokenizer.ggml.eos_token_id", eos_id)

    P = lambda a: np.ascontiguousarray(np.asarray(a, np.float32))
    w.add_tensor_f32("token_embd.weight", P(params["tok_emb"]))
    w.add_tensor_f32("output_norm.weight", P(params["out_norm_w"]))
    w.add_tensor_f32("output.weight", P(params["lm_head"]).T)
    lp = params["layers"]
    for i in range(cfg.n_layers):
        pre = f"blk.{i}."
        w.add_tensor_f32(pre + "attn_norm.weight", P(lp["attn_norm_w"][i]))
        w.add_tensor_f32(pre + "attn_q.weight", permute_to_interleaved(
            P(lp["wq"][i]).T, cfg.n_heads))
        w.add_tensor_f32(pre + "attn_k.weight", permute_to_interleaved(
            P(lp["wk"][i]).T, cfg.n_kv_heads))
        w.add_tensor_f32(pre + "attn_v.weight", P(lp["wv"][i]).T)
        w.add_tensor_f32(pre + "attn_output.weight", P(lp["wo"][i]).T)
        w.add_tensor_f32(pre + "ffn_norm.weight", P(lp["mlp_norm_w"][i]))
        if cfg.n_experts:
            w.add_tensor_f32(pre + "ffn_gate_inp.weight",
                             P(lp["router"][i]).T)
            if moe_merged:
                # ggml layout [E, F, D] (row-major) gate/up, [E, D, F] down
                w.add_tensor_f32(pre + "ffn_gate_exps.weight",
                                 P(lp["we_gate"][i]).transpose(0, 2, 1))
                w.add_tensor_f32(pre + "ffn_up_exps.weight",
                                 P(lp["we_up"][i]).transpose(0, 2, 1))
                w.add_tensor_f32(pre + "ffn_down_exps.weight",
                                 P(lp["we_down"][i]).transpose(0, 2, 1))
            else:
                for e in range(cfg.n_experts):
                    w.add_tensor_f32(pre + f"ffn_gate.{e}.weight",
                                     P(lp["we_gate"][i, e]).T)
                    w.add_tensor_f32(pre + f"ffn_up.{e}.weight",
                                     P(lp["we_up"][i, e]).T)
                    w.add_tensor_f32(pre + f"ffn_down.{e}.weight",
                                     P(lp["we_down"][i, e]).T)
        else:
            w.add_tensor_f32(pre + "ffn_gate.weight", P(lp["w_gate"][i]).T)
            w.add_tensor_f32(pre + "ffn_up.weight", P(lp["w_up"][i]).T)
            w.add_tensor_f32(pre + "ffn_down.weight", P(lp["w_down"][i]).T)
    w.write()


def test_gguf_roundtrip_logits_match(tmp_path):
    """Params → GGUF (llama.cpp layout) → transcode → identical logits."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    path = str(tmp_path / "tiny.gguf")
    write_tiny_llama_gguf(path, cfg, params)

    with GGUFFile(path) as f:
        cfg2 = TC.config_from_gguf(f)
        assert cfg2.dim == cfg.dim
        assert cfg2.n_kv_heads == cfg.n_kv_heads
        assert cfg2.head_dim == cfg.head_dim
        params2 = TC.load_params(f, cfg2, dtype=np.float32)

    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 9)))
    ref, _, _ = decoder.prefill_chunk(params, cfg, tokens)
    p2 = jax.tree_util.tree_map(jnp.asarray, params2)
    out, _, _ = decoder.prefill_chunk(p2, cfg2, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_store_cache_roundtrip(tmp_path):
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    gguf_path = str(tmp_path / "m.gguf")
    write_tiny_llama_gguf(gguf_path, cfg, params)

    cache = str(tmp_path / "cache")
    digest = TC.content_fingerprint(gguf_path)
    cfg1, params1, tok1 = TC.load_model(gguf_path, cache_dir=cache,
                                        dtype=np.float32)
    # second load must come from the store (delete the gguf to prove it;
    # pass the digest explicitly as a registry-driven caller would)
    import os
    os.remove(gguf_path)
    cfg2, params2, tok2 = TC.load_model(gguf_path, cache_dir=cache,
                                        dtype=np.float32, digest=digest)
    assert cfg1 == cfg2
    assert tok1["tokenizer.ggml.model"] == "llama"
    for (k1, v1), (k2, v2) in zip(
            sorted(TC._flatten(params1)), sorted(TC._flatten(params2))):
        assert k1 == k2
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))


def test_bf16_transcode(tmp_path):
    import ml_dtypes
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    gguf_path = str(tmp_path / "m.gguf")
    write_tiny_llama_gguf(gguf_path, cfg, params)
    cfg1, params1, _ = TC.load_model(gguf_path,
                                     cache_dir=str(tmp_path / "c"),
                                     dtype=ml_dtypes.bfloat16)
    assert params1["tok_emb"].dtype == ml_dtypes.bfloat16
    x = jnp.asarray(params1["tok_emb"])
    assert x.dtype == jnp.bfloat16
