"""LLaVA multimodal: vision tower correctness, mmproj GGUF transcode,
embeds prefill equivalence, engine multimodal admission, and the full
HTTP path with a base64 image."""

import base64
import io
import json
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.gguf import writer as W
from ollama_operator_tpu.gguf.reader import GGUFFile
from ollama_operator_tpu.gguf import transcode as TC
from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.models import vision as V
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

rng = np.random.default_rng(21)
F32 = jnp.float32


def test_patchify_matches_naive():
    cfg = V.TINY_VISION
    img = rng.standard_normal((2, 16, 16, 3)).astype(np.float32)
    got = np.asarray(V.patchify(cfg, jnp.asarray(img)))
    P, n = cfg.patch_size, cfg.n_patches_side
    for b in range(2):
        for pi in range(n):
            for pj in range(n):
                patch = img[b, pi * P:(pi + 1) * P, pj * P:(pj + 1) * P, :]
                want = patch.transpose(2, 0, 1).reshape(-1)  # (c, i, j)
                np.testing.assert_allclose(got[b, pi * n + pj], want)


def test_encode_shape_and_select_layer():
    cfg = V.TINY_VISION
    params = V.init_params(cfg, jax.random.PRNGKey(0))
    img = jnp.asarray(rng.standard_normal((2, 16, 16, 3)), F32)
    out = V.encode(cfg, params, img)
    assert out.shape == (2, cfg.n_patches, cfg.proj_dim)
    # select_layer=-2 must differ from running all layers
    import dataclasses
    cfg_all = dataclasses.replace(cfg, select_layer=-1)
    out_all = V.encode(cfg_all, params, img)
    assert not np.allclose(np.asarray(out), np.asarray(out_all))


def write_tiny_mmproj(path, cfg, params):
    """Export vision params in llama.cpp clip mmproj conventions."""
    w = W.GGUFWriter(path)
    P_ = lambda a: np.ascontiguousarray(np.asarray(a, np.float32))
    w.add_meta("general.architecture", "clip")
    w.add_meta("clip.vision.image_size", cfg.image_size)
    w.add_meta("clip.vision.patch_size", cfg.patch_size)
    w.add_meta("clip.vision.embedding_length", cfg.width)
    w.add_meta("clip.vision.feed_forward_length", cfg.ffn_dim)
    w.add_meta("clip.vision.block_count", cfg.n_layers)
    w.add_meta("clip.vision.attention.head_count", cfg.n_heads)
    w.add_meta("clip.vision.attention.layer_norm_epsilon", cfg.norm_eps)
    Pp = cfg.patch_size
    w.add_tensor_f32("v.patch_embd.weight",
                     P_(params["patch_emb"]).T.reshape(cfg.width, 3, Pp, Pp))
    w.add_tensor_f32("v.class_embd", P_(params["class_emb"]))
    w.add_tensor_f32("v.position_embd.weight", P_(params["pos_emb"]))
    w.add_tensor_f32("v.pre_ln.weight", P_(params["pre_ln_w"]))
    w.add_tensor_f32("v.pre_ln.bias", P_(params["pre_ln_b"]))
    w.add_tensor_f32("mm.0.weight", P_(params["mm_0"]).T)
    w.add_tensor_f32("mm.0.bias", P_(params["mm_0_b"]))
    w.add_tensor_f32("mm.2.weight", P_(params["mm_2"]).T)
    w.add_tensor_f32("mm.2.bias", P_(params["mm_2_b"]))
    lp = params["layers"]
    for i in range(cfg.n_layers):
        pre = f"v.blk.{i}."
        w.add_tensor_f32(pre + "ln1.weight", P_(lp["ln1_w"][i]))
        w.add_tensor_f32(pre + "ln1.bias", P_(lp["ln1_b"][i]))
        w.add_tensor_f32(pre + "ln2.weight", P_(lp["ln2_w"][i]))
        w.add_tensor_f32(pre + "ln2.bias", P_(lp["ln2_b"][i]))
        for nm, key in (("attn_q", "wq"), ("attn_k", "wk"),
                        ("attn_v", "wv"), ("attn_out", "wo")):
            w.add_tensor_f32(pre + nm + ".weight", P_(lp[key][i]).T)
            w.add_tensor_f32(pre + nm + ".bias",
                             P_(lp["b" + key[1]][i]))
        w.add_tensor_f32(pre + "ffn_up.weight", P_(lp["w_up"][i]).T)
        w.add_tensor_f32(pre + "ffn_up.bias", P_(lp["b_up"][i]))
        w.add_tensor_f32(pre + "ffn_down.weight", P_(lp["w_down"][i]).T)
        w.add_tensor_f32(pre + "ffn_down.bias", P_(lp["b_down"][i]))
    w.write()


def test_mmproj_gguf_roundtrip(tmp_path):
    cfg = V.TINY_VISION
    params = V.init_params(cfg, jax.random.PRNGKey(1))
    path = str(tmp_path / "mmproj.gguf")
    write_tiny_mmproj(path, cfg, params)
    with GGUFFile(path) as f:
        cfg2 = TC.vision_config_from_gguf(f)
        assert (cfg2.image_size, cfg2.patch_size, cfg2.width) == (
            cfg.image_size, cfg.patch_size, cfg.width)
        # proj_dim falls back to mm.2 out-dim
        assert cfg2.proj_dim == cfg.proj_dim
        # mmproj files are pre-trimmed by the llava converter → run all
        assert cfg2.select_layer == -1
        p2 = TC.load_vision_params(f, cfg2)
    img = jnp.asarray(rng.standard_normal((1, 16, 16, 3)), F32)
    import dataclasses
    ref = V.encode(dataclasses.replace(cfg, select_layer=-1), params, img)
    got = V.encode(cfg2, jax.tree_util.tree_map(jnp.asarray, p2), img)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_prefill_inputs_embeds_equivalent():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0,
                                cfg.vocab_size)
    ref, rk, rv = decoder.prefill_chunk(params, cfg, tokens)
    embeds = params["tok_emb"][tokens]
    got, gk, gv = decoder.prefill_chunk(params, cfg, tokens,
                                        inputs_embeds=embeds)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(gk), np.asarray(rk))


def test_engine_admit_embeds_matches_tokens():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(2), dtype=F32)
    ecfg = EngineConfig(max_slots=2, max_seq_len=64, min_prefill_bucket=8,
                        cache_dtype=F32)
    opts = SlotOptions(temperature=0.0)
    prompt = np.asarray(rng.integers(1, cfg.vocab_size, 11), np.int32)

    e1 = Engine(cfg, params, ecfg=ecfg)
    t1 = [e1.admit(0, prompt, opts)]
    t1 += [int(t[0]) for t in e1.decode_n(4)]

    e2 = Engine(cfg, params, ecfg=ecfg)
    embeds = np.asarray(params["tok_emb"])[prompt].astype(np.float32)
    t2 = [e2.admit(0, prompt, opts, embeds=embeds)]
    t2 += [int(t[0]) for t in e2.decode_n(4)]
    assert t1 == t2


@pytest.fixture(scope="module")
def mm_stack(tmp_path_factory):
    """Tiny llava: tiny llama LLM + tiny vision tower through the full
    registry → pull → server stack."""
    import jax.numpy as jnp_
    from fake_registry import FakeRegistry
    from test_transcode import write_tiny_llama_gguf
    from ollama_operator_tpu.runtime.engine import EngineConfig
    from ollama_operator_tpu.server.app import ModelManager, serve

    tmp = tmp_path_factory.mktemp("mm")
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    gguf_path = str(tmp / "tiny.gguf")
    write_tiny_llama_gguf(gguf_path, cfg, params)

    import dataclasses
    vcfg = dataclasses.replace(V.TINY_VISION, proj_dim=cfg.dim)
    vparams = V.init_params(vcfg, jax.random.PRNGKey(3))
    proj_path = str(tmp / "mmproj.gguf")
    write_tiny_mmproj(proj_path, vcfg, vparams)

    reg = FakeRegistry()
    url = reg.start()
    reg.add_model("library", "tinyllava", "latest",
                  open(gguf_path, "rb").read(),
                  template="{{ .Prompt }}",
                  params={"temperature": 0.0, "num_predict": 6},
                  projector_bytes=open(proj_path, "rb").read())
    manager = ModelManager(str(tmp / "store"), cache_dir=str(tmp / "cache"),
                           ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                             cache_dtype=jnp_.float32,
                                             min_prefill_bucket=16),
                           engine_dtype="float32")
    httpd = serve(manager, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield {"base": base, "registry_url": url}
    httpd.shutdown()
    reg.stop()


def _png_b64(arr_u8):
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr_u8, "RGB").save(buf, "PNG")
    return base64.b64encode(buf.getvalue()).decode()


def test_generate_with_image_e2e(mm_stack):
    ref = f"{mm_stack['registry_url']}/library/tinyllava:latest"
    req = urllib.request.Request(
        mm_stack["base"] + "/api/pull",
        data=json.dumps({"model": ref, "stream": False}).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=300)

    img = rng.integers(0, 255, (20, 20, 3), dtype=np.uint8)
    body = {"model": ref, "prompt": "describe", "stream": False,
            "images": [_png_b64(img)],
            "options": {"temperature": 0, "num_predict": 4}}
    req = urllib.request.Request(
        mm_stack["base"] + "/api/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    out = json.loads(urllib.request.urlopen(req, timeout=600).read())
    assert out["done"]
    assert out["eval_count"] >= 1

    # the same prompt without the image: the prompt_eval_count difference
    # must be exactly the image token count (llava counts image tokens)
    body2 = {"model": ref, "prompt": "describe", "stream": False,
             "options": {"temperature": 0, "num_predict": 4}}
    req2 = urllib.request.Request(
        mm_stack["base"] + "/api/generate", data=json.dumps(body2).encode(),
        headers={"Content-Type": "application/json"})
    out2 = json.loads(urllib.request.urlopen(req2, timeout=600).read())
    n_img_tokens = V.TINY_VISION.n_patches
    assert (out["prompt_eval_count"] - out2["prompt_eval_count"]
            == n_img_tokens)
