"""Scale-to-zero warm restore: engine warm-state snapshot/restore and the
image-store PVC persistence (gguf/store.py warm_snapshot helpers).

The contract under test is the wake path's recompile budget: a replica
cold-started from a warm snapshot must register the full warm plan and
serve its first streams with `tpu_model_recompiles_total` untouched —
byte-identical to a replica that ran the full warm_buckets() pass.

The serialized-executable payload path (TPU_WARM_SNAPSHOT_EXECS) is
deliberately disabled here — and is off by default on the CPU backend
(Engine._snapshot_execs_ok): this host's CPU-backend executable
deserialization is unstable (see conftest.py's note on the persistent
compilation cache), and the payloads are best-effort by design — a
snapshot of signatures alone must already deliver the zero-recompile
wake, just with compile time instead of deserialize time.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pickle
import pytest

from ollama_operator_tpu.gguf.store import (load_warm_snapshot,
                                            save_warm_snapshot,
                                            warm_snapshot_path)
from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                SlotOptions)
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

rng = np.random.default_rng(47)

# two prefill buckets (16, 32) keep the per-test compile bill small; the
# snapshot/restore logic is bucket-count-independent
ECFG = EngineConfig(max_slots=2, max_seq_len=32, min_prefill_bucket=16,
                    cache_dtype=jnp.float32, decode_chunk=4)


def tiny(**kw):
    base = cfglib.PRESETS["tiny"]
    return cfglib.ModelConfig(**{**base.__dict__, **kw}).validate()


@pytest.fixture(scope="module")
def model():
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(5),
                                 dtype=jnp.float32)
    return cfg, params


@pytest.fixture(scope="module")
def donor_blob(model):
    """One fully-warmed donor engine, snapshotted; compile passes are the
    whole cost of this module, so every test shares this snapshot."""
    cfg, params = model
    donor = Engine(cfg, params, ecfg=ECFG)
    donor.warm_buckets()
    assert donor._warmed_sigs
    blob = donor.warm_snapshot()
    return set(donor._warmed_sigs), blob


@pytest.fixture(autouse=True)
def _sigs_only(monkeypatch):
    monkeypatch.setenv("TPU_WARM_SNAPSHOT_EXECS", "0")


def _recompile_total():
    return sum(METRICS.get("tpu_model_recompiles_total", f'{{kind="{k}"}}')
               for k in ("decode", "admit", "admit_many", "extend", "spec"))


class TestEngineSnapshot:
    def test_warm_restored_engine_serves_without_recompiles(
            self, model, donor_blob):
        """Acceptance: cold start from snapshot, then dispatch — the
        recompile counter delta stays 0 vs > 0 for the no-snapshot
        control arm, and the decoded tokens are identical."""
        cfg, params = model
        sigs, blob = donor_blob
        prompt = np.asarray(rng.integers(1, cfg.vocab_size, 11), np.int32)
        opts = SlotOptions(temperature=0.0)

        warmed = Engine(cfg, params, ecfg=ECFG)
        out = warmed.restore_warm(blob)
        assert out["restored"] + out["compiled"] == len(sigs)
        assert warmed._warmed_sigs == sigs
        # the restore itself counted zero recompiles...
        assert all(v == 0 for v in warmed.recompiles.values())
        total0 = _recompile_total()
        t_warm = warmed.admit(0, prompt, opts)
        warm_toks = [np.asarray(warmed.decode_n()) for _ in range(3)]
        # ...and so did the first post-wake dispatches
        assert _recompile_total() == total0          # zero-recompile wake
        assert all(v == 0 for v in warmed.recompiles.values())

        control = Engine(cfg, params, ecfg=ECFG)     # no snapshot
        t_ctl = control.admit(0, prompt, opts)
        ctl_toks = [np.asarray(control.decode_n()) for _ in range(3)]
        assert _recompile_total() > total0           # control recompiles
        assert sum(control.recompiles.values()) > 0

        assert t_warm == t_ctl
        for a, b in zip(warm_toks, ctl_toks):
            np.testing.assert_array_equal(a, b)

    def test_version_and_backend_mismatch_falls_back_to_recompile(
            self, model, donor_blob):
        cfg, params = model
        _, blob = donor_blob
        snap = pickle.loads(blob)
        snap["jax"] = "0.0.0"                  # incompatible provenance
        snap["sigs"] = snap["sigs"][:2]        # keep the compile bill tiny
        snap["execs"] = {}
        eng = Engine(cfg, params, ecfg=ECFG)
        out = eng.restore_warm(pickle.dumps(snap))
        assert out["restored"] == 0
        assert out["compiled"] == 2
        assert len(eng._warmed_sigs) == 2
        assert all(v == 0 for v in eng.recompiles.values())

    def test_unknown_snapshot_version_rejected(self, model):
        cfg, params = model
        eng = Engine(cfg, params, ecfg=ECFG)
        with pytest.raises(ValueError):
            eng.restore_warm(pickle.dumps({"version": 99}))


def test_exec_payloads_are_accelerator_only_by_default(monkeypatch):
    """Unset TPU_WARM_SNAPSHOT_EXECS must NOT ship executable payloads
    on the CPU backend (deserialization there is unstable on some hosts
    — the original default-on corrupted a reloading server): a CPU
    snapshot carries signatures only, and a CPU restore ignores any
    exec payloads a blob does carry.  "1" forces the path back on."""
    monkeypatch.delenv("TPU_WARM_SNAPSHOT_EXECS", raising=False)
    assert jax.default_backend() == "cpu"
    assert Engine._snapshot_execs_ok() is False
    monkeypatch.setenv("TPU_WARM_SNAPSHOT_EXECS", "1")
    assert Engine._snapshot_execs_ok() is True
    monkeypatch.setenv("TPU_WARM_SNAPSHOT_EXECS", "0")
    assert Engine._snapshot_execs_ok() is False


class TestSnapshotStore:
    def test_roundtrip(self, tmp_path):
        blob = b"\x00warm\xff" * 100
        path = save_warm_snapshot(str(tmp_path), "abc123", blob)
        assert path == warm_snapshot_path(str(tmp_path), "abc123")
        assert load_warm_snapshot(str(tmp_path), "abc123") == blob
        # last-finisher-wins overwrite, reader never sees a torn file
        save_warm_snapshot(str(tmp_path), "abc123", b"v2")
        assert load_warm_snapshot(str(tmp_path), "abc123") == b"v2"

    def test_missing_is_none(self, tmp_path):
        assert load_warm_snapshot(str(tmp_path), "nope") is None
