# repo-local developer tooling; `python -m tools.invariant_lint` needs this
