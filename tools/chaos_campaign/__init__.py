"""Seeded randomized chaos campaigns against a real in-process fleet.

``python -m tools.chaos_campaign --seed 7 --events 40`` builds the
harness (tools/chaos_campaign/harness.py), runs one campaign through the
generic engine (runtime/chaos.py), and exits non-zero with the seed and
the minimal event prefix on any invariant violation. The CI
``chaos-campaign`` job runs several seeds per push and appends each
report to the step summary.
"""

from .harness import ChaosFleet, DeterministicReplica, expected_text

__all__ = ["ChaosFleet", "DeterministicReplica", "expected_text"]
