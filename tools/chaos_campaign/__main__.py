"""CLI entry: one campaign per --seed, reports to stdout (and
GITHUB_STEP_SUMMARY when set)."""

from __future__ import annotations

import argparse
import os
import sys
import tempfile


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.chaos_campaign",
        description="seeded randomized chaos campaign against a real "
                    "in-process fleet (runtime/chaos.py)")
    ap.add_argument("--seed", type=int, action="append", required=True,
                    help="campaign seed (repeatable: one campaign each)")
    ap.add_argument("--events", type=int, default=40,
                    help="events per campaign (default 40)")
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--engine-canary", action="store_true",
                    help="ride a real tiny Engine+Scheduler along so the "
                         "engine-family fault points fire (needs jax)")
    ap.add_argument("--disagg", action="store_true",
                    help="split the fleet into prefill/decode pools and "
                         "add the kill_prefill_mid_handoff action")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.engine_canary:
        # CPU determinism for the canary, same as the test tier
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from ollama_operator_tpu.runtime.chaos import (InvariantViolation,
                                                   run_campaign)

    from .harness import ChaosFleet

    summary_path = os.environ.get("GITHUB_STEP_SUMMARY")
    say = (lambda _m: None) if args.quiet else \
        (lambda m: print(m, flush=True))
    all_lines = []
    for seed in args.seed:
        with tempfile.TemporaryDirectory(prefix="chaos-") as td:
            fleet = ChaosFleet(n_replicas=args.replicas, persist_dir=td,
                               engine_canary=args.engine_canary,
                               disagg=args.disagg)
            try:
                report = run_campaign(fleet, seed, args.events, log=say)
            except InvariantViolation as e:
                print(f"CHAOS CAMPAIGN FAILED\n{e}", file=sys.stderr,
                      flush=True)
                if summary_path:
                    with open(summary_path, "a") as f:
                        f.write(f"## chaos campaign seed {seed}: "
                                f"FAILED\n```\n{e}\n```\n")
                return 1
            finally:
                fleet.close()
        lines = report.summary_lines()
        lines.append(f"  - stream outcomes: {fleet.outcomes()}")
        for ln in lines:
            print(ln, flush=True)
        all_lines.extend(lines)
    if summary_path:
        with open(summary_path, "a") as f:
            f.write("## chaos campaigns\n"
                    + "\n".join(f"- {ln.strip()}" for ln in all_lines)
                    + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
