"""The real chaos-campaign fleet (runtime/chaos.py supplies the engine).

Builds everything the campaign schedule can break, in one process:

- K deterministic HTTP replicas (text is a pure function of the request,
  so byte-identity is checkable after any number of failovers/resumes);
- one :class:`~ollama_operator_tpu.operator.gateway.Gateway` in front,
  scraping them on a fast period, with crash-recovery persistence ON —
  the ``kill_gateway`` action crashes it and boots a replacement from
  the same journal;
- a leader→follower control-plane pair (runtime/follower.py) pinged
  every round, with a ``partition_leader`` action that goes silent and
  asserts the follower fails static within TPU_CP_LEADER_TIMEOUT_S;
- a stub kube apiserver polled through the real retrying KubeClient;
- optionally a real tiny Engine + Scheduler canary (the same stack the
  scheduler tests use) so the engine-family fault points
  (engine.step/admit, pages.alloc, detok.feed, scheduler.replay) fire
  against real page tables, with the page-accounting invariant checked
  after every event.

Global invariants (``check``): every finished client stream reached a
terminal state exactly once — a typed error XOR a complete,
byte-identical stream; robustness counters are monotonic; live page
tables pass their accounting check. At quiesce (``check(final=True)``):
the gateway journal has drained, the epoch quarantine is empty, and the
thread census is back to within slack of the post-setup baseline.
"""

from __future__ import annotations

import hashlib
import http.client
import http.server
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ollama_operator_tpu.operator import client as kclient
from ollama_operator_tpu.operator.gateway import Gateway
from ollama_operator_tpu.runtime import follower as fol
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

# counters that must never decrease while a campaign runs
MONOTONIC_COUNTERS = (
    "tpu_model_gateway_persist_writes_total",
    "tpu_model_gateway_persist_restores_total",
    "tpu_model_gateway_drain_total",
    "tpu_model_leader_lost_total",
    "tpu_model_followers_lost_total",
    "tpu_model_engine_restarts_total",
)

# small pool so the affinity/prefix paths actually get repeat prefixes
_PROMPTS = ("tell me about pod %d", "summarize doc %d please",
            "translate item %d", "why is replica %d slow")


def gen_pieces(key: str, n: int) -> List[str]:
    """Deterministic 'model': piece i is a pure function of the request
    key and position — any replica, and any resumed splice, must
    regenerate identical text."""
    return [" " + hashlib.sha256(f"{key}|{i}".encode()).hexdigest()[:4]
            for i in range(n)]


def request_key(body: Dict[str, Any]) -> str:
    prompt = (body.get("system") or "") + (body.get("prompt") or "")
    o = body.get("options") or {}
    if float(o.get("temperature", 0.7)) == 0.0:
        return f"greedy|{prompt}"
    return f"sampled|{prompt}|seed={o.get('seed')}"


def expected_text(body: Dict[str, Any]) -> str:
    o = body.get("options") or {}
    return "".join(gen_pieces(request_key(body),
                              int(o.get("num_predict", 8))))


class DeterministicReplica:
    """One fake backend. ``ctl['down']`` = socket-level death;
    ``ctl['die_after']`` severs the next stream after N frames and then
    stays down (death mid-stream, the failover trigger);
    ``ctl['export_down']`` makes the replica die exactly when the KV
    export pull arrives (a prefill replica killed mid-handoff).
    ``pool`` marks a disagg role: a prefill-pool replica honors the
    gateway's ``options.disagg_prefill`` cap and finishes with
    ``done_reason: "handoff"`` after the first token."""

    def __init__(self, pool: str = "") -> None:
        self.ctl: Dict[str, Any] = {"down": False, "die_after": None,
                                    "export_down": False}
        self.pool = pool
        self._lock = threading.Lock()
        self.seen: List[str] = []
        replica = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *_a):
                pass

            def _down(self) -> bool:
                if replica.ctl["down"]:
                    self.close_connection = True
                    self.connection.close()
                    return True
                return False

            def _json(self, obj, status=200):
                data = json.dumps(obj).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self._down():
                    return
                if self.path == "/readyz":
                    self._json({"status": "ok"})
                elif self.path == "/api/ps":
                    self._json({"models": [{
                        "name": "chaos", "utilization": {"occupancy": 0.1},
                        "lifecycle": {"state": "serving",
                                      "active_streams": 0},
                        "admission": {"queued_by_class": {}},
                    }]})
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):
                if self._down():
                    return
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n)) if n else {}
                if self.path == "/api/prefix_probe":
                    prompt = ((body.get("system") or "")
                              + (body.get("prompt") or ""))
                    best = 0
                    with replica._lock:
                        for s in replica.seen:
                            k = 0
                            for a, b in zip(s, prompt):
                                if a != b:
                                    break
                                k += 1
                            best = max(best, k)
                    self._json({"model": body.get("model"),
                                "matched_tokens": best // 4,
                                "prompt_tokens": len(prompt) // 4})
                elif self.path in ("/api/generate", "/api/chat"):
                    self._generate(body)
                elif self.path == "/api/kv_export":
                    self._kv_export(body)
                elif self.path == "/api/kv_import":
                    self._kv_import(body)
                else:
                    self._json({"ok": True})

            def _kv_export(self, body):
                if replica.ctl["export_down"]:
                    # the drill: prefill replica dies exactly when the
                    # decode replica comes to pull its pages
                    replica.ctl["export_down"] = False
                    replica.ctl["down"] = True
                    self.close_connection = True
                    self.connection.close()
                    return
                prompt = ((body.get("system") or "")
                          + (body.get("prompt") or ""))
                blob = hashlib.sha256(prompt.encode()).digest() * 8
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def _kv_import(self, body):
                src = body.get("source") or ""
                fwd = {k: body.get(k) for k in ("model", "prompt",
                                                "system") if body.get(k)}
                try:
                    pull = urllib.request.Request(
                        f"{src}/api/kv_export",
                        data=json.dumps(fwd).encode(),
                        headers={"Content-Type": "application/json"})
                    with urllib.request.urlopen(pull, timeout=5) as r:
                        blob = r.read()
                except Exception:  # noqa: BLE001 — source died mid-pull
                    self._json({"error": "kv pull failed",
                                "imported_pages": 0}, 502)
                    return
                self._json({"imported_pages": max(1, len(blob) // 64),
                            "bytes": len(blob)})

            def _chunk(self, data: bytes):
                self.wfile.write(f"{len(data):x}\r\n".encode() + data
                                 + b"\r\n")
                self.wfile.flush()

            def _generate(self, body):
                prompt = ((body.get("system") or "")
                          + (body.get("prompt") or ""))
                o = body.get("options") or {}
                n = int(o.get("num_predict", 8))
                # the gateway's disagg prefill leg caps at one token and
                # expects done_reason "handoff" (options.disagg_prefill)
                prefill_only = bool(o.get("disagg_prefill"))
                pieces = gen_pieces(request_key(body), n)
                if prefill_only:
                    pieces = pieces[:1]
                with replica._lock:
                    replica.seen.append(prompt)
                    die_after = replica.ctl["die_after"]
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                for i, piece in enumerate(pieces):
                    if die_after is not None and i >= die_after:
                        replica.ctl["die_after"] = None
                        replica.ctl["down"] = True
                        self.close_connection = True
                        self.connection.close()
                        return
                    self._chunk(json.dumps(
                        {"model": body.get("model"), "response": piece,
                         "done": False}).encode() + b"\n")
                if prefill_only and die_after is not None:
                    # killed mid-handoff: the first token went out but
                    # the handoff frame never arrives — the gateway must
                    # downgrade to journal replay on the decode pool
                    replica.ctl["die_after"] = None
                    replica.ctl["down"] = True
                    self.close_connection = True
                    self.connection.close()
                    return
                self._chunk(json.dumps(
                    {"model": body.get("model"), "response": "",
                     "done": True,
                     "done_reason": ("handoff" if prefill_only
                                     else "stop"),
                     "eval_count": len(pieces)}).encode() + b"\n")
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                     Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class _StubKube:
    """Minimal apiserver: answers every GET with one Pod object (the
    real retrying KubeClient in front of it is what the kube.request
    fault point exercises)."""

    def __init__(self) -> None:
        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *_a):
                pass

            def do_GET(self):
                data = json.dumps({
                    "apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": "chaos-0",
                                 "namespace": "default"}}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                     Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        host, port = self.httpd.server_address
        self.url = f"http://{host}:{port}"

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


class _Client(threading.Thread):
    """One end-to-end stream with the reconnect contract a real client
    follows: transport failures (gateway crash mid-stream) retry with
    the SAME request_id against the current gateway; typed HTTP errors
    and in-stream error frames are terminal."""

    ATTEMPTS = 8

    def __init__(self, fleet: "ChaosFleet", body: Dict[str, Any]):
        super().__init__(daemon=True, name="chaos-client")
        self.fleet = fleet
        self.body = body
        self.expected = expected_text(body)
        self.outcome: Optional[str] = None   # ok | error | shed | lost
        self.detail = ""
        self.terminals = 0

    def _stream_once(self) -> Optional[str]:
        """One attempt; returns an outcome or None (retry)."""
        req = urllib.request.Request(
            f"{self.fleet.base_url}/api/generate",
            data=json.dumps(self.body).encode(), method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=20) as resp:
                raw = resp.read().decode()
        except urllib.error.HTTPError as e:
            # a typed HTTP error is a clean terminal answer; drain and
            # all-ejected shed must carry Retry-After >= 1 (the computed
            # remediation hint) — its absence is an invariant violation
            if e.code in (429, 503):
                # a shed is NOT a stream terminal: the gateway never
                # committed a stream, it told the client to come back
                ra = e.headers.get("Retry-After")
                try:
                    ok_hint = ra is not None and int(ra) >= 1
                except ValueError:
                    ok_hint = False
                if not ok_hint:
                    self.detail = f"503 without usable Retry-After: {ra!r}"
                    return "lost"
                return "shed"
            self.terminals += 1
            self.detail = f"http {e.code}"
            return "error"
        except (urllib.error.URLError, http.client.HTTPException,
                ConnectionError, socket.timeout, OSError):
            return None                       # transport: reconnect
        frames = [json.loads(ln) for ln in raw.splitlines() if ln.strip()]
        errs = [f for f in frames if f.get("error")]
        dones = [f for f in frames if f.get("done")]
        if errs:
            self.terminals += 1
            if dones:
                self.detail = "error frame AND done frame in one stream"
                return "lost"
            self.detail = str(errs[0].get("error"))[:200]
            return "error"
        if not dones:
            return None                       # truncated: reconnect
        self.terminals += 1
        text = "".join(f.get("response") or "" for f in frames)
        if text != self.expected:
            self.detail = (f"byte mismatch: got {text!r} "
                           f"expected {self.expected!r}")
            return "lost"
        return "ok"

    def run(self) -> None:
        sheds = 0
        try:
            for _ in range(self.ATTEMPTS):
                out = self._stream_once()
                if out == "shed":
                    sheds += 1
                    time.sleep(0.1)       # honor the hint, scaled down
                    continue
                if out is not None:
                    self.outcome = out
                    return
                time.sleep(0.1)
            # never got a terminal: sheds all the way down is a clean
            # typed answer each time; transport losses are not
            self.outcome = "shed-exhausted" if sheds else "lost"
        except Exception as e:  # noqa: BLE001 — a client crash IS a violation
            self.detail = f"client crashed: {type(e).__name__}: {e}"
            self.outcome = "lost"


class ChaosFleet:
    """Harness for :func:`ollama_operator_tpu.runtime.chaos.run_campaign`
    — see the protocol in runtime/chaos.py."""

    def __init__(self, n_replicas: int = 3, persist_dir: str = ".",
                 engine_canary: bool = False, disagg: bool = False):
        self._env_prev: Dict[str, Optional[str]] = {}
        self._set_env({
            "TPU_GATEWAY_EJECT_FAILURES": "2",
            "TPU_GATEWAY_EJECT_S": "0.3",
            "TPU_GATEWAY_SLOW_SCRAPE_MS": "400",
            "TPU_GATEWAY_PERSIST": os.path.join(
                persist_dir, "chaos-gateway-journal.ndjson"),
            "TPU_GATEWAY_PERSIST_FLUSH_MS": "5",
            "TPU_CP_LEADER_TIMEOUT_S": "0.4",
            "TPU_CP_SEND_TIMEOUT_S": "5",
            "TPU_DRAIN_TIMEOUT_S": "5",
            "TPU_DISAGG_HANDOFF_TIMEOUT_S": "5",
        })
        self.disagg = disagg
        # disagg mode: one prefill replica, the rest decode — the
        # handoff machinery (and its death drills) fire on real traffic
        pools = ((["prefill"] + ["decode"] * max(1, n_replicas - 1))
                 if disagg else [""] * n_replicas)
        self.replicas = [DeterministicReplica(pool=p) for p in pools]
        self._gw_lock = threading.Lock()
        self.gw = self._boot_gateway()
        self.kube = _StubKube()
        self.kc = kclient.KubeClient(self.kube.url, timeout=5)
        self._cp: Optional[fol.ControlPlane] = None
        self._fol_thread: Optional[threading.Thread] = None
        self._boot_control_plane()
        self.canary = None
        if engine_canary:
            self.canary = _EngineCanary()
        self.ledger: List[_Client] = []
        self._pending: List[_Client] = []
        self._counter_floor = {n: METRICS.get(n)
                               for n in MONOTONIC_COUNTERS}
        self._seq = 0
        # thread census AFTER full setup: the final check asserts we
        # return to within slack of this, so nothing the campaign spawns
        # (pumps, clients, replacement gateways) may leak
        self._thread_floor = threading.active_count()

    # -- plumbing --------------------------------------------------------

    def _set_env(self, kv: Dict[str, str]) -> None:
        for k, v in kv.items():
            self._env_prev.setdefault(k, os.environ.get(k))
            os.environ[k] = v

    def _boot_gateway(self) -> Gateway:
        gw = Gateway(replicas=[(f"rep-{i}", r.url, r.pool)
                               for i, r in enumerate(self.replicas)],
                     scrape_period_s=0.1, port=0)
        return gw.start()

    def _boot_control_plane(self) -> None:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        self._cp = fol.ControlPlane(1, port, bind="127.0.0.1",
                                    heartbeat_s=0)
        t = threading.Thread(
            target=fol.run_follower, args=(None, "127.0.0.1", port),
            daemon=True, name="chaos-follower")
        t.start()
        self._fol_thread = t

    @property
    def base_url(self) -> str:
        with self._gw_lock:
            return self.gw.base_url

    # -- chaos actions (beyond what FAULTS can express) ------------------

    @property
    def actions(self) -> Dict[str, Any]:
        out = {
            "kill_replica": self.kill_replica,
            "revive_replica": self.revive_replica,
            "die_mid_stream": self.die_mid_stream,
            "kill_gateway": self.kill_gateway,
            "partition_leader": self.partition_leader,
        }
        if self.disagg:
            out["kill_prefill_mid_handoff"] = self.kill_prefill_mid_handoff
        return out

    def kill_replica(self, rng) -> None:
        r = rng.choice(self.replicas)
        r.ctl["down"] = True

    def revive_replica(self, rng) -> None:
        down = [r for r in self.replicas if r.ctl["down"]]
        if down:
            r = rng.choice(down)
            r.ctl["down"] = False
            r.ctl["die_after"] = None
            r.ctl["export_down"] = False

    def die_mid_stream(self, rng) -> None:
        live = [r for r in self.replicas if not r.ctl["down"]]
        if live:
            rng.choice(live).ctl["die_after"] = rng.randint(1, 4)

    def kill_prefill_mid_handoff(self, rng) -> None:
        """The disagg acceptance drill: a prefill replica dies in the
        middle of a handoff. Two timings, both of which must downgrade
        to journal replay on the decode pool with zero client error
        frames: before the handoff frame (first token out, stream
        severed) or at the KV export pull (decode replica's import
        finds a corpse)."""
        live = [r for r in self.replicas
                if r.pool == "prefill" and not r.ctl["down"]]
        if not live:
            return
        r = rng.choice(live)
        if rng.random() < 0.5:
            r.ctl["die_after"] = 1
        else:
            r.ctl["export_down"] = True

    def kill_gateway(self, rng) -> None:
        """Crash (no drain — stop() only flushes what the window already
        buffered) and boot a replacement from the same persist log. Any
        client mid-stream reconnects with its request_id and must get a
        byte-identical splice or one clean error frame."""
        with self._gw_lock:
            old = self.gw
            old.stop()
            self.gw = self._boot_gateway()

    def partition_leader(self, rng) -> None:
        """Leader goes silent (no close — the socket stays open): the
        follower must fail static within TPU_CP_LEADER_TIMEOUT_S, then a
        fresh pair joins (the restarted pod rejoining the next world)."""
        t = self._fol_thread
        assert t is not None
        t.join(timeout=5.0)
        assert not t.is_alive(), (
            "follower still blocked on a silent leader after the "
            "TPU_CP_LEADER_TIMEOUT_S watchdog window")
        if self._cp is not None:
            self._cp.close()
        self._boot_control_plane()

    # -- campaign protocol ----------------------------------------------

    def traffic(self, rng) -> None:
        # reap finished clients into the ledger
        still = []
        for c in self._pending:
            (still if c.outcome is None else self.ledger).append(c)
        self._pending = still
        for _ in range(rng.randint(1, 3)):
            self._seq += 1
            kind = rng.choice(("greedy", "seeded", "sampled"))
            opts: Dict[str, Any] = {"num_predict": rng.randint(4, 10)}
            if kind == "greedy":
                opts["temperature"] = 0
            else:
                opts["temperature"] = 0.9
                if kind == "seeded":
                    opts["seed"] = rng.randint(1, 1 << 20)
            body = {"model": "chaos",
                    "prompt": rng.choice(_PROMPTS) % rng.randint(0, 3),
                    "stream": True, "options": opts,
                    "request_id": f"chaos-{self._seq}"}
            c = _Client(self, body)
            c.start()
            self._pending.append(c)
        # control-plane leg: one broadcast (follower.send fires here); a
        # lost follower degrades the world → model the pod restart
        cp = self._cp
        if cp is not None:
            try:
                cp.broadcast(("ping",))
            except fol.FollowerLost:
                cp.close()
                if self._fol_thread is not None:
                    self._fol_thread.join(timeout=5.0)
                self._boot_control_plane()
        # operator leg: one reconciler-style read through the retrying
        # client (kube.request fires inside); an exhausted retry budget
        # is what the reconcile loop would just retry next pass
        try:
            self.kc.get("v1", "Pod", "default", "chaos-0")
        except kclient.ApiError:
            pass  # lint: allow(exception-hygiene): retry budget exhausted — next reconcile pass retries
        if self.canary is not None:
            self.canary.traffic(rng)

    def check(self, final: bool = False) -> None:
        for c in list(self.ledger):
            assert c.terminals <= 1 and c.outcome != "lost", (
                f"stream {c.body['request_id']} violated "
                f"exactly-once-terminal: outcome={c.outcome} "
                f"terminals={c.terminals} {c.detail}")
        for name in MONOTONIC_COUNTERS:
            now = METRICS.get(name)
            assert now >= self._counter_floor[name], (
                f"{name} went backwards: {self._counter_floor[name]} "
                f"-> {now}")
            self._counter_floor[name] = now
        from ollama_operator_tpu.runtime.paged import live_tables
        for pt in live_tables():
            pt.check()
        if not final:
            return
        # quiesce-only invariants
        for c in self.ledger:
            assert c.outcome in ("ok", "error", "shed", "shed-exhausted"), (
                f"stream {c.body['request_id']} never reached a terminal "
                f"state: {c.outcome} {c.detail}")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if self.gw.journal_stats()["live"] == 0:
                break
            time.sleep(0.05)
        assert self.gw.journal_stats()["live"] == 0, (
            f"gateway journal not drained at quiesce: "
            f"{self.gw.journal_stats()}")
        for pt in live_tables():
            assert pt.quarantined == 0, (
                f"{pt.quarantined} page(s) stuck in epoch quarantine "
                f"at quiesce")
        # thread census: transient pumps/clients must have exited (old
        # gateways' scrape threads need a tick to observe _stop)
        slack = 6
        while time.monotonic() < deadline:
            if threading.active_count() <= self._thread_floor + slack:
                break
            time.sleep(0.05)
        assert threading.active_count() <= self._thread_floor + slack, (
            f"thread leak: {threading.active_count()} live vs baseline "
            f"{self._thread_floor} (+{slack} slack): "
            f"{sorted(t.name for t in threading.enumerate())}")

    def quiesce(self) -> None:
        for r in self.replicas:
            r.ctl["down"] = False
            r.ctl["die_after"] = None
            r.ctl["export_down"] = False
        for c in self._pending:
            c.join(timeout=30)
            # outcome None after the join = a hung stream; the final
            # check's allowed-outcome assert reports it as a violation
            self.ledger.append(c)
        self._pending = []
        if self.canary is not None:
            self.canary.quiesce()

    def close(self) -> None:
        with self._gw_lock:
            self.gw.stop()
        if self._cp is not None:
            self._cp.close()
        if self._fol_thread is not None:
            self._fol_thread.join(timeout=5.0)
        for r in self.replicas:
            r.stop()
        self.kube.stop()
        if self.canary is not None:
            self.canary.close()
        for k, v in self._env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # -- report helpers --------------------------------------------------

    def outcomes(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for c in self.ledger:
            out[c.outcome or "in-flight"] = out.get(c.outcome or
                                                    "in-flight", 0) + 1
        return out


class _EngineCanary:
    """A real tiny Engine + Scheduler riding along so the engine-family
    fault points fire against real page tables. A restart-budget
    exhaustion (scheduler broken) models the pod restart: rebuild."""

    def __init__(self) -> None:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ollama_operator_tpu.models import config as cfglib
        from ollama_operator_tpu.models import decoder
        from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                        SlotOptions)
        from ollama_operator_tpu.runtime.scheduler import Scheduler
        self._np = np
        self._greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
        cfg = cfglib.PRESETS["tiny"]
        params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                     dtype=jnp.float32)

        def build():
            eng = Engine(cfg, params,
                         ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                           cache_dtype=jnp.float32,
                                           min_prefill_bucket=16))
            return Scheduler(eng, restart_backoff=0.01)

        self._build = build
        self.sched = build()
        self.rebuilds = 0
        # prewarm: take the XLA compiles now so the first campaign round
        # isn't a seconds-long stall that desyncs every timing knob
        r = self.sched.submit(np.array([1, 2], np.int32), self._greedy,
                              max_tokens=2)
        list(r.tokens())

    def traffic(self, rng) -> None:
        if self.sched.broken:
            self.sched.shutdown()
            self.sched = self._build()
            self.rebuilds += 1
        toks = self._np.array(
            [rng.randint(1, 200) for _ in range(rng.randint(2, 6))],
            self._np.int32)
        try:
            r = self.sched.submit(toks, self._greedy,
                                  max_tokens=rng.randint(2, 5))
            list(r.tokens())
        except RuntimeError:
            pass  # lint: allow(exception-hygiene): injected per-request error — the recovery path under test
        except Exception as e:  # noqa: BLE001
            raise AssertionError(
                f"engine canary saw an untyped failure: "
                f"{type(e).__name__}: {e}") from e

    def quiesce(self) -> None:
        if self.sched.broken:
            self.sched.shutdown()
            self.sched = self._build()
            self.rebuilds += 1
        r = self.sched.submit(self._np.array([7, 8], self._np.int32),
                              self._greedy, max_tokens=2)
        assert len(list(r.tokens())) == 2, \
            "engine canary cannot serve after quiesce"

    def close(self) -> None:
        self.sched.shutdown()
