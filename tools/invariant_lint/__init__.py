"""Invariant linter: project-specific AST passes enforcing the runtime's
hand-maintained invariants at diff time (see core.py for the model and
passes/ for the catalog).  Run with ``python -m tools.invariant_lint``
or ``make lint``."""

from .core import Finding, LintConfig, Pass, Project, run_passes
from .passes import ALL_PASSES

__all__ = ["Finding", "LintConfig", "Pass", "Project", "run_passes",
           "ALL_PASSES"]
