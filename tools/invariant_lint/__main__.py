"""CLI: ``python -m tools.invariant_lint [options]``.

Exit code 0 when every finding is suppressed (or none exist), 1
otherwise — `make lint` and the static-analysis CI job gate on it.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from .core import (LintConfig, render_github, render_json,
                   render_summary_markdown, render_text, run_passes,
                   summarize)
from .passes import ALL_PASSES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="invariant_lint",
        description="Project invariant linter (8 AST passes; see "
                    "CONTRIBUTING.md 'Invariant linter')")
    ap.add_argument("--root", default=None,
                    help="repo root (default: two levels above this "
                         "package)")
    ap.add_argument("--format", choices=("text", "json", "github"),
                    default="text")
    ap.add_argument("--only", default=None,
                    help="comma-separated pass ids to run")
    ap.add_argument("--verbose", action="store_true",
                    help="text format: include suppressed findings")
    ap.add_argument("--summary", default=None, metavar="FILE",
                    help="append a per-pass markdown summary table "
                         "(GitHub job summary)")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_passes:
        for p in ALL_PASSES:
            print(f"{p.id:22s} {p.summary}")
        return 0

    root = Path(args.root) if args.root else \
        Path(__file__).resolve().parents[2]
    config = LintConfig(root=root)
    only = args.only.split(",") if args.only else None
    findings = run_passes(config, ALL_PASSES, only=only)

    if args.format == "json":
        print(render_json(ALL_PASSES, findings))
    elif args.format == "github":
        out = render_github(findings)
        if out:
            print(out)
    else:
        out = render_text(findings, verbose=args.verbose)
        if out:
            print(out)

    rows = summarize(ALL_PASSES, findings)
    unsuppressed = sum(r["findings"] for r in rows)
    suppressed = sum(r["suppressed"] for r in rows)
    if args.format == "text":
        print(f"invariant-lint: {unsuppressed} finding(s), "
              f"{suppressed} suppressed, "
              f"{len([r for r in rows if r['id'] not in ('suppression', 'parse')])} passes",
              file=sys.stderr)

    if args.summary:
        with open(args.summary, "a", encoding="utf-8") as f:
            f.write(render_summary_markdown(ALL_PASSES, findings) + "\n")

    return 1 if unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
