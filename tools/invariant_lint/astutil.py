"""Shared AST helpers: function indexing and name-based call resolution.

The passes that reason about call graphs (host-sync-hot-path, lock-order)
resolve calls *by name*: ``self.foo()`` or ``x.foo()`` reaches every
function/method named ``foo`` defined in the analyzed scope.  That is
deliberately conservative — Python offers no static dispatch — and works
well here because the runtime uses distinct method names for distinct
roles.  Receivers that are clearly library modules (np/jax/os/...) are
excluded so the graph doesn't absorb library internals.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

# receivers that are library namespaces, never project objects
IGNORED_RECEIVERS = {
    "np", "jnp", "jax", "numpy", "os", "time", "math", "re", "json",
    "threading", "queue", "struct", "pickle", "socket", "sys", "logging",
    "itertools", "functools", "collections", "random", "dataclasses",
    "weakref", "http", "urllib", "subprocess", "signal", "ast",
}

# Method names shared with builtin containers/IO objects.  A call like
# ``self._rules.pop(...)`` or ``c.close()`` on a non-self receiver is
# overwhelmingly a dict/list/socket operation; resolving it by bare name
# to a runtime class's ``pop``/``close`` manufactures call edges (and
# with them lock-order cycles) that don't exist.  Non-self attribute
# calls with these names are therefore not resolved.
GENERIC_METHODS = {
    "get", "pop", "popitem", "setdefault", "clear", "remove", "discard",
    "append", "appendleft", "extend", "add", "update", "insert", "index",
    "count", "sort", "reverse", "copy", "items", "keys", "values",
    "close", "open", "read", "write", "flush", "send", "recv", "put",
    "join", "wait", "set", "start", "cancel", "done", "empty", "full",
    "qsize", "acquire", "release",
}

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class FuncInfo:
    rel: str                    # module path, repo-relative
    name: str                   # bare function/method name
    qualname: str               # Class.method or module-level name
    cls: Optional[str]          # enclosing class name, if a method
    node: ast.AST               # the FunctionDef


def index_functions(sources: Dict[str, "object"],
                    scope_rels: List[str]) -> Dict[str, List[FuncInfo]]:
    """name -> FuncInfos for every top-level function and class method in
    the given modules.  Nested defs are NOT indexed (in this codebase
    they are overwhelmingly jit-traced device code)."""
    index: Dict[str, List[FuncInfo]] = {}
    for rel in scope_rels:
        src = sources[rel]
        for node in src.tree.body:
            if isinstance(node, FUNC_NODES):
                fi = FuncInfo(rel, node.name, node.name, None, node)
                index.setdefault(node.name, []).append(fi)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, FUNC_NODES):
                        fi = FuncInfo(rel, sub.name,
                                      f"{node.name}.{sub.name}",
                                      node.name, sub)
                        index.setdefault(sub.name, []).append(fi)
    return index


def own_statements(func: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's body, skipping nested function/class defs (jit
    bodies trace on-device; a host-sync primitive there is tracing, not
    a sync).  Lambdas ARE included — they run host-side when called."""
    stack: List[ast.AST] = list(getattr(func, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, FUNC_NODES + (ast.ClassDef,)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def receiver_root(expr: ast.AST) -> Optional[str]:
    """Leftmost name of an attribute chain: a.b.c -> 'a'."""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def callee_name(call: ast.Call) -> Tuple[Optional[str], Optional[str]]:
    """(name, receiver_root) of a call.  receiver_root is None for bare
    calls; library receivers return (None, root) so the caller skips."""
    f = call.func
    if isinstance(f, ast.Name):
        return f.id, None
    if isinstance(f, ast.Attribute):
        root = receiver_root(f.value)
        if root in IGNORED_RECEIVERS:
            return None, root
        return f.attr, root
    return None, None


def calls_in(func: ast.AST) -> Iterator[ast.Call]:
    for node in own_statements(func):
        if isinstance(node, ast.Call):
            yield node


def resolve_call(call: ast.Call, enclosing_cls: Optional[str],
                 index: Dict[str, List[FuncInfo]]) -> List[FuncInfo]:
    """Candidate targets of a call, name-resolved with three precision
    tiers:

    - ``helper()`` (bare name) — every indexed function of that name;
    - ``self.foo()`` — the enclosing class's own ``foo`` when it defines
      one, else the name-wide candidates if they all live on one class;
    - ``obj.foo()`` — skipped for library receivers and
      GENERIC_METHODS names; otherwise resolved only when every
      candidate lives on the same class (an ambiguous name like a
      ``pop`` defined on two classes yields nothing — a deliberate
      under-approximation that keeps the lock graph honest).
    """
    f = call.func
    if isinstance(f, ast.Name):
        return list(index.get(f.id, ()))
    if not isinstance(f, ast.Attribute):
        return []
    cands = index.get(f.attr, ())
    if not cands:
        return []
    if isinstance(f.value, ast.Name) and f.value.id == "self":
        if enclosing_cls is not None:
            own = [fi for fi in cands if fi.cls == enclosing_cls]
            if own:
                return own
    else:
        root = receiver_root(f.value)
        if root in IGNORED_RECEIVERS or f.attr in GENERIC_METHODS:
            return []
    classes = {fi.cls for fi in cands}
    if len(classes) == 1:
        return list(cands)
    return []


def reachable(index: Dict[str, List[FuncInfo]],
              roots: List[FuncInfo],
              stop_names: Set[str]) -> List[FuncInfo]:
    """BFS over the name-resolved call graph.  ``stop_names`` are
    traversed-to but not through (sanctioned boundaries)."""
    seen: Set[int] = set()
    order: List[FuncInfo] = []
    work = list(roots)
    while work:
        fi = work.pop()
        if id(fi.node) in seen:
            continue
        seen.add(id(fi.node))
        order.append(fi)
        if fi.name in stop_names:
            continue
        for call in calls_in(fi.node):
            for target in resolve_call(call, fi.cls, index):
                if id(target.node) not in seen:
                    work.append(target)
    return order


def fstring_static_text(node: ast.AST) -> Optional[str]:
    """The constant parts of a string literal or f-string, or None when
    the node is not string-like.  Used to extract label KEYS (static)
    from label strings whose VALUES are interpolated."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("\x00")      # interpolation marker
        return "".join(parts)
    return None
