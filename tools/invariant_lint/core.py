"""Invariant-linter core: file walker, finding model, suppressions, runner.

Eleven PRs of runtime invariants — pre-seeded metric families, host-only
flight-recorder events, replay determinism, a host-sync-free dispatch hot
path — were enforced only by runtime spot checks and reviewer memory.
This package makes them diff-time checks: a pluggable set of AST passes
over the tree (stdlib ``ast`` only, zero dependencies, same philosophy as
runtime/trace.py), each producing findings that must be fixed or
explicitly suppressed inline::

    # lint: allow(<pass-id>): <reason>

A suppression covers findings of that pass on the same line or the line
directly below the comment (so it can sit above a multi-line construct).
A suppression without a reason string is itself a finding — the whole
point is that every intentional violation carries its justification in
the tree.

The pass catalog lives in :mod:`tools.invariant_lint.passes`; project
geometry (which files are hot-path roots, where the knob registry lives)
is a :class:`LintConfig`, so the test-suite fixtures can lint miniature
trees with the exact same machinery.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\(([a-z0-9_-]+)\)(?::\s*(.*?))?\s*(?:#|$)")

SEVERITIES = ("error", "warning")


@dataclasses.dataclass
class Finding:
    """One invariant violation at a source location."""

    path: str           # repo-relative, posix separators
    line: int           # 1-based
    pass_id: str
    message: str
    severity: str = "error"
    suppressed: bool = False
    suppress_reason: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "pass": self.pass_id,
            "severity": self.severity,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.path}:{self.line}: [{self.pass_id}] "
                f"{self.severity}: {self.message}{tag}")


class Source:
    """A parsed Python file plus its inline suppressions."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=rel)
        # line -> {pass_id: reason or None}; a comment suppresses findings
        # on its own line and on the line directly below it
        self.suppressions: Dict[int, Dict[str, Optional[str]]] = {}
        for i, ln in enumerate(self.lines, start=1):
            if "lint:" not in ln:
                continue
            for m in SUPPRESS_RE.finditer(ln):
                reason = (m.group(2) or "").strip() or None
                self.suppressions.setdefault(i, {})[m.group(1)] = reason

    def suppression_for(self, pass_id: str,
                        line: int) -> Tuple[bool, Optional[str]]:
        for at in (line, line - 1):
            entry = self.suppressions.get(at)
            if entry and pass_id in entry:
                return True, entry[pass_id]
        return False, None


@dataclasses.dataclass
class LintConfig:
    """Project geometry the passes need.  Paths are repo-relative."""

    root: Path
    # directories (or single files) walked for Python sources
    code_roots: Tuple[str, ...] = ("ollama_operator_tpu",)
    # knob registry + the docs trees whose knob tables must list every
    # declared knob
    knobs_module: str = "ollama_operator_tpu/runtime/knobs.py"
    docs_roots: Tuple[str, ...] = ("docs/en", "docs/zh-CN")
    knob_prefix: str = "TPU_"
    # metric registry module holding describe() + pre-seed calls
    metrics_module: str = "ollama_operator_tpu/server/metrics.py"
    metric_prefix: str = "tpu_model_"
    # fault-point catalog module: every FAULTS.check() site must name a
    # point registered here, and the docs fault-point tables must list
    # every registered point
    faults_module: str = "ollama_operator_tpu/runtime/faults.py"
    # host-sync pass: (module rel path, function/method name) roots of
    # the dispatch-critical call graph, and names at which traversal
    # stops (sanctioned materialisation points: DecodeHandle.wait is THE
    # place device results come home)
    hot_roots: Tuple[Tuple[str, str], ...] = (
        ("ollama_operator_tpu/runtime/engine.py", "decode_n_launch"),
        ("ollama_operator_tpu/runtime/engine.py", "step"),
        ("ollama_operator_tpu/runtime/scheduler.py", "_fanout"),
    )
    hot_stop_names: Tuple[str, ...] = ("wait", "_watched")
    # modules whose call graphs the hot-path/lock passes resolve into
    graph_scopes: Tuple[str, ...] = ("ollama_operator_tpu/runtime",
                                     "ollama_operator_tpu/server/metrics.py")
    # broadcast-purity: the follower module and its handler entrypoints
    follower_module: str = "ollama_operator_tpu/runtime/follower.py"
    follower_handlers: Tuple[str, ...] = ("run_follower",)
    follower_forbidden: Tuple[str, ...] = (
        "FLIGHT", "TRACER", "Tracer", "get_tracer", "NULL_TRACE",
        "METRICS", "AdmissionQueue", "ADMISSION")
    # determinism: replay-relevant modules (PR 9 bit-identical restart
    # replay depends on these)
    determinism_modules: Tuple[str, ...] = (
        "ollama_operator_tpu/runtime/engine.py",
        "ollama_operator_tpu/runtime/follower.py",
    )
    # exception-hygiene scopes
    exception_scopes: Tuple[str, ...] = (
        "ollama_operator_tpu/runtime",
        "ollama_operator_tpu/server",
        "ollama_operator_tpu/operator",
    )


class Project:
    """Parsed sources + config handed to every pass."""

    def __init__(self, config: LintConfig):
        self.config = config
        self.sources: Dict[str, Source] = {}
        self.parse_errors: List[Finding] = []
        for rel in self._walk():
            path = config.root / rel
            try:
                text = path.read_text(encoding="utf-8")
                self.sources[rel] = Source(path, rel, text)
            except (SyntaxError, UnicodeDecodeError) as e:
                lineno = getattr(e, "lineno", 1) or 1
                self.parse_errors.append(Finding(
                    rel, lineno, "parse", f"cannot parse: {e}"))

    def _walk(self) -> List[str]:
        rels: List[str] = []
        for root in self.config.code_roots:
            p = self.config.root / root
            if p.is_file():
                rels.append(root)
                continue
            for f in sorted(p.rglob("*.py")):
                rel = f.relative_to(self.config.root).as_posix()
                if "__pycache__" in rel:
                    continue
                rels.append(rel)
        # the knob/metric/fault registries may live outside code_roots
        # (fixture trees)
        for extra in (self.config.knobs_module, self.config.metrics_module,
                      self.config.faults_module):
            p = self.config.root / extra
            if p.is_file() and extra not in rels:
                rels.append(extra)
        return rels

    def source(self, rel: str) -> Optional[Source]:
        return self.sources.get(rel)

    def in_scope(self, rel: str, scopes: Iterable[str]) -> bool:
        return any(rel == s or rel.startswith(s.rstrip("/") + "/")
                   for s in scopes)


class Pass:
    """Base class: subclasses set ``id``/``summary`` and implement run()."""

    id: str = ""
    summary: str = ""

    def run(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


def _apply_suppressions(project: Project,
                        findings: List[Finding]) -> List[Finding]:
    out: List[Finding] = []
    for f in findings:
        src = project.source(f.path)
        if src is not None:
            hit, reason = src.suppression_for(f.pass_id, f.line)
            if hit:
                f.suppressed = True
                f.suppress_reason = reason
                if reason is None:
                    # a suppression with no justification is a finding of
                    # its own — the reason string IS the policy
                    out.append(Finding(
                        f.path, f.line, "suppression",
                        f"allow({f.pass_id}) has no reason string; write "
                        f"'# lint: allow({f.pass_id}): <why>'"))
        out.append(f)
    return out


def run_passes(config: LintConfig, passes: Iterable[Pass],
               only: Optional[Iterable[str]] = None) -> List[Finding]:
    """Parse the tree once, run every pass, apply suppressions.

    Findings come back sorted by (path, line); ``parse`` errors (files the
    walker could not parse) are always included.
    """
    project = Project(config)
    selected = list(passes)
    if only is not None:
        wanted = set(only)
        selected = [p for p in selected if p.id in wanted]
    findings: List[Finding] = list(project.parse_errors)
    for p in selected:
        findings.extend(p.run(project))
    findings = _apply_suppressions(project, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return findings


def summarize(passes: Iterable[Pass],
              findings: List[Finding]) -> List[dict]:
    rows = []
    ids = [p.id for p in passes] + ["suppression", "parse"]
    for pid in ids:
        mine = [f for f in findings if f.pass_id == pid]
        rows.append({
            "id": pid,
            "findings": sum(1 for f in mine if not f.suppressed),
            "suppressed": sum(1 for f in mine if f.suppressed),
        })
    return rows


def render_text(findings: List[Finding], verbose: bool = False) -> str:
    shown = [f for f in findings if verbose or not f.suppressed]
    return "\n".join(f.render() for f in shown)


def render_json(passes: Iterable[Pass], findings: List[Finding]) -> str:
    return json.dumps({
        "version": 1,
        "passes": summarize(passes, findings),
        "findings": [f.to_dict() for f in findings],
    }, indent=2)


def render_github(findings: List[Finding]) -> str:
    out = []
    for f in findings:
        if f.suppressed:
            continue
        kind = "error" if f.severity == "error" else "warning"
        msg = f.message.replace("%", "%25").replace("\n", "%0A")
        out.append(f"::{kind} file={f.path},line={f.line},"
                   f"title=invariant-lint [{f.pass_id}]::{msg}")
    return "\n".join(out)


def render_summary_markdown(passes: Iterable[Pass],
                            findings: List[Finding]) -> str:
    rows = summarize(passes, findings)
    lines = ["### Invariant linter", "",
             "| pass | findings | suppressed |",
             "| --- | ---: | ---: |"]
    for r in rows:
        lines.append(f"| `{r['id']}` | {r['findings']} | "
                     f"{r['suppressed']} |")
    total = sum(r["findings"] for r in rows)
    lines.append("")
    lines.append(f"**{total} unsuppressed finding(s)** "
                 f"({'gate fails' if total else 'gate passes'})")
    return "\n".join(lines)
