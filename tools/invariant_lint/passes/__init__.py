"""Pass catalog.  Adding a pass: subclass core.Pass, give it a unique
kebab-case ``id`` and a one-line ``summary``, implement ``run(project)``
returning Findings, and append an instance to ALL_PASSES.  Fixture
coverage in tests/fixtures/lint/ + tests/test_invariant_lint.py is part
of the definition of done (see CONTRIBUTING.md)."""

from .determinism import DeterminismPass
from .exception_hygiene import ExceptionHygienePass
from .fault_catalog import FaultCatalogPass
from .follower_purity import FollowerPurityPass
from .host_sync import HostSyncPass
from .knob_registry import KnobRegistryPass
from .lock_order import LockOrderPass
from .metrics_discipline import MetricsDisciplinePass

ALL_PASSES = [
    KnobRegistryPass(),
    MetricsDisciplinePass(),
    FaultCatalogPass(),
    HostSyncPass(),
    LockOrderPass(),
    FollowerPurityPass(),
    DeterminismPass(),
    ExceptionHygienePass(),
]

__all__ = ["ALL_PASSES", "KnobRegistryPass", "MetricsDisciplinePass",
           "FaultCatalogPass", "HostSyncPass", "LockOrderPass",
           "FollowerPurityPass", "DeterminismPass",
           "ExceptionHygienePass"]
