"""determinism: replay-relevant modules stay bit-identical across runs.

PR 9's restart recovery replays in-flight streams bit-identically; the
follower (PR 1) replays the leader's whole call stream.  Both depend on
the engine/follower modules being deterministic functions of their call
arguments.  Flagged here:

- ``time.time()`` — wall clock differs across processes and restarts
  (``time.monotonic`` for durations/metrics is fine: it never feeds
  token or page decisions);
- stdlib ``random.*`` / ``np.random.*`` — per-process entropy
  (``jax.random`` is keyed and explicitly derived, always allowed);
- iteration over *sets* of slots/pages/signatures without ``sorted()``
  — set iteration order is salted per process, so a loop over a set
  that touches device state replays in a different order on the
  follower.  Detected for set literals, ``set(...)`` calls, set
  comprehensions, and attributes assigned from them.
"""

from __future__ import annotations

import ast
from typing import List, Set

from ..astutil import receiver_root
from ..core import Finding, Pass, Project


class DeterminismPass(Pass):
    id = "determinism"
    summary = ("no wall-clock/process entropy/unsorted set iteration in "
               "replay-relevant modules")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for rel in project.config.determinism_modules:
            src = project.source(rel)
            if src is None:
                continue
            set_names = self._set_names(src.tree)
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Call):
                    msg = self._call_violation(node)
                    if msg:
                        findings.append(Finding(rel, node.lineno,
                                                self.id, msg))
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    msg = self._iter_violation(node.iter, set_names)
                    if msg:
                        findings.append(Finding(rel, node.lineno,
                                                self.id, msg))
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    for gen in node.generators:
                        msg = self._iter_violation(gen.iter, set_names)
                        if msg:
                            findings.append(Finding(rel, node.lineno,
                                                    self.id, msg))
        return findings

    @staticmethod
    def _set_names(tree: ast.AST) -> Set[str]:
        """Bare/attr names assigned from set constructors anywhere in
        the module (tracked by terminal name only)."""
        names: Set[str] = set()

        def is_set_expr(v: ast.AST) -> bool:
            if isinstance(v, (ast.Set, ast.SetComp)):
                return True
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Name):
                return v.func.id in ("set", "frozenset")
            return False

        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.Assign) and is_set_expr(node.value):
                targets = node.targets
            elif (isinstance(node, ast.AnnAssign) and node.value is not None
                  and is_set_expr(node.value)):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    names.add(t.attr)
        return names

    @staticmethod
    def _call_violation(call: ast.Call) -> str:
        f = call.func
        if isinstance(f, ast.Attribute):
            root = receiver_root(f.value)
            if f.attr == "time" and root == "time":
                return ("time.time() is wall clock — replay across "
                        "restart/follower diverges; use call arguments "
                        "or time.monotonic for durations")
            if root in ("random",):
                return (f"stdlib random.{f.attr} is per-process entropy "
                        f"— use jax.random with an explicit key")
            if (isinstance(f.value, ast.Attribute)
                    and f.value.attr == "random"
                    and receiver_root(f.value) in ("np", "numpy")):
                return (f"np.random.{f.attr} is per-process entropy — "
                        f"use jax.random with an explicit key")
        return ""

    @staticmethod
    def _iter_violation(it: ast.AST, set_names: Set[str]) -> str:
        def describe(expr: ast.AST) -> str:
            if isinstance(expr, ast.Set):
                return "a set literal"
            if isinstance(expr, ast.SetComp):
                return "a set comprehension"
            if (isinstance(expr, ast.Call)
                    and isinstance(expr.func, ast.Name)
                    and expr.func.id == "set"):
                return "set(...)"
            name = None
            if isinstance(expr, ast.Name):
                name = expr.id
            elif isinstance(expr, ast.Attribute):
                name = expr.attr
            if name in set_names:
                return f"the set {name!r}"
            return ""

        what = describe(it)
        if what:
            return (f"iteration over {what} is salted per process — "
                    f"wrap in sorted() so replay order is deterministic")
        return ""
