"""exception-hygiene: no silently swallowed exceptions.

PR 2 made the serving path crash-only — failures are supposed to reach
the supervisor, the flight recorder, or a typed error, never vanish.
In ``runtime/``, ``server/`` and ``operator/``:

- a bare ``except:`` is always an error (it eats KeyboardInterrupt and
  SystemExit too);
- ``except Exception:`` (or ``BaseException``) whose body only
  ``pass``/``continue``-es requires a justified inline suppression —
  best-effort teardown is legitimate, but the reason must be written
  down at the site.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Pass, Project

BROAD = ("Exception", "BaseException")


def _is_broad(type_node) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Name):
        return type_node.id in BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


def _swallows(body) -> bool:
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)):
            continue            # docstring / ellipsis
        return False
    return True


class ExceptionHygienePass(Pass):
    id = "exception-hygiene"
    summary = ("no bare except; swallowed broad excepts need a "
               "justified suppression")

    def run(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for rel, src in project.sources.items():
            if not project.in_scope(rel, project.config.exception_scopes):
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if node.type is None:
                    findings.append(Finding(
                        rel, node.lineno, self.id,
                        "bare except: swallows KeyboardInterrupt/"
                        "SystemExit — catch a typed exception"))
                elif _is_broad(node.type) and _swallows(node.body):
                    findings.append(Finding(
                        rel, node.lineno, self.id,
                        "except Exception with an empty body swallows "
                        "failures silently — narrow it, handle it, or "
                        "justify with # lint: allow(exception-hygiene): "
                        "<why>"))
        return findings
