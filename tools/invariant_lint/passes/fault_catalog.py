"""fault-catalog: every FAULTS.check site catalogued and documented.

``runtime/faults.py`` keeps an introspectable catalog of fault points —
module-level ``point("name", "site", "doc")`` registrations — which is
what makes a randomized chaos campaign (runtime/chaos.py) possible: the
schedule is drawn from ``FAULTS.points()``, so a check site missing from
the catalog is a recovery path chaos can never reach. This pass
cross-checks four surfaces:

- **uncatalogued check** — code calls ``FAULTS.check("x")`` with a point
  name the catalog does not register;
- **non-literal check** — a ``FAULTS.check`` site whose point name is
  computed: the catalog (and the chaos scheduler behind it) can only
  enumerate literals;
- **stale catalog entry** — a registered point with no ``FAULTS.check``
  site left in the tree (the campaign would arm it forever for nothing);
- **undocumented point** — a registered point absent from a docs tree's
  fault-point tables (docs/en AND docs/zh-CN must both list every
  point, same contract as the knob tables).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from ..core import Finding, Pass, Project


class FaultCatalogPass(Pass):
    id = "fault-catalog"
    summary = ("FAULTS.check sites registered in the fault-point catalog "
               "and listed in both docs fault-point tables")

    def run(self, project: Project) -> List[Finding]:
        cfg = project.config
        findings: List[Finding] = []

        catalog = self._catalog(project)
        if not catalog:
            findings.append(Finding(
                cfg.faults_module, 1, self.id,
                "fault-point catalog is missing or registers nothing — "
                "module-level point(name, site, doc) calls expected"))

        checked: Set[str] = set()
        for rel, src in project.sources.items():
            if rel == cfg.faults_module:
                continue
            for node in ast.walk(src.tree):
                name, line, literal = self._check_site(node)
                if line is None:
                    continue
                if not literal:
                    findings.append(Finding(
                        rel, line, self.id,
                        "FAULTS.check with a computed point name — the "
                        "catalog can only enumerate literal points; "
                        "inline the name"))
                    continue
                checked.add(name)
                if catalog and name not in catalog:
                    findings.append(Finding(
                        rel, line, self.id,
                        f"fault point \"{name}\" is checked here but not "
                        f"registered in {cfg.faults_module} — add a "
                        f"point(\"{name}\", site, doc) entry"))

        for name, line in sorted(catalog.items()):
            if name not in checked:
                findings.append(Finding(
                    cfg.faults_module, line, self.id,
                    f"fault point \"{name}\" is registered but no "
                    f"FAULTS.check(\"{name}\") site remains — remove the "
                    f"stale catalog entry"))

        for root, mentioned in self._docs_mentions(project,
                                                   catalog).items():
            for name, line in sorted(catalog.items()):
                if name not in mentioned:
                    findings.append(Finding(
                        cfg.faults_module, line, self.id,
                        f"fault point \"{name}\" is registered but "
                        f"missing from the {root} fault-point tables"))
        return findings

    # -- catalog ---------------------------------------------------------

    def _catalog(self, project: Project) -> Dict[str, int]:
        src = project.source(project.config.faults_module)
        if src is None:
            return {}
        out: Dict[str, int] = {}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name != "point" or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value,
                                                              str):
                out[first.value] = node.lineno
        return out

    # -- check sites -----------------------------------------------------

    @staticmethod
    def _check_site(node: ast.AST) -> Tuple[str, int, bool]:
        """(point name, line, is_literal) for a FAULTS.check call, else
        ("", None, False)."""
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "check"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "FAULTS"
                and node.args):
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                return arg.value, node.lineno, True
            return "", node.lineno, False
        return "", None, False

    # -- docs ------------------------------------------------------------

    def _docs_mentions(self, project: Project,
                       catalog: Dict[str, int]) -> Dict[str, Set[str]]:
        out: Dict[str, Set[str]] = {}
        for root in project.config.docs_roots:
            base = project.config.root / root
            mentioned: Set[str] = set()
            if base.is_dir():
                for md in sorted(base.rglob("*.md")):
                    try:
                        text = md.read_text(encoding="utf-8")
                    except UnicodeDecodeError:
                        continue
                    for name in catalog:
                        if name in text:
                            mentioned.add(name)
            out[root] = mentioned
        return out
