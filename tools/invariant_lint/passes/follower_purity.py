"""follower-purity: broadcast op handlers touch no host-only singletons.

PR 7 pinned it in a docstring; this pass pins it in CI: the follower's
broadcast-replay loop (``run_follower`` and everything it calls inside
``runtime/follower.py``) must not touch host-only singletons — the
flight recorder, tracers, admission policy state, the metrics registry.
Followers replay the leader's call stream; anything keyed to leader-side
wall-clock or policy state would desynchronise the replay, and
flight-recorder events must never enter the broadcast stream.

A follower recording into its *own* per-process ring is legitimate
observability — that one site carries an inline suppression saying so,
which is exactly the invariant made reviewable.
"""

from __future__ import annotations

import ast
from typing import List

from ..astutil import FUNC_NODES, callee_name, index_functions, reachable
from ..core import Finding, Pass, Project


class FollowerPurityPass(Pass):
    id = "follower-purity"
    summary = ("broadcast op handlers must not touch FLIGHT/Tracer/"
               "admission/metrics singletons")

    def run(self, project: Project) -> List[Finding]:
        cfg = project.config
        src = project.source(cfg.follower_module)
        if src is None:
            return []
        index = index_functions(project.sources, [cfg.follower_module])
        roots = [fi for name in cfg.follower_handlers
                 for fi in index.get(name, ())]
        handlers = reachable(index, roots, set())

        forbidden = set(cfg.follower_forbidden)
        findings: List[Finding] = []
        for fi in handlers:
            for node in ast.walk(fi.node):
                if isinstance(node, FUNC_NODES + (ast.ClassDef,)):
                    if node is not fi.node:
                        continue
                name = None
                if isinstance(node, ast.Name):
                    name = node.id
                elif isinstance(node, ast.Attribute):
                    name = node.attr
                if name in forbidden:
                    findings.append(Finding(
                        fi.rel, node.lineno, self.id,
                        f"broadcast handler {fi.qualname} touches "
                        f"host-only singleton {name} — policy/"
                        f"observability state must never enter the "
                        f"follower replay path"))
        # dedup attribute+name double hits on the same reference
        seen = set()
        out = []
        for f in findings:
            key = (f.path, f.line, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out
