"""host-sync-hot-path: no device->host sync inside the dispatch path.

The dispatch-critical call graph — rooted at ``engine.decode_n_launch``,
``engine.step``, and ``scheduler._fanout`` — must never synchronise with
the device: a ``.item()``, ``jax.device_get``, ``block_until_ready``, or
``np.asarray`` on a device array stalls the double-buffered pipeline
(PR 3/PR 5) and shows up as the dispatch-overhead cliffs BENCH_r05
recorded.  ``DecodeHandle.wait`` is THE sanctioned materialisation point
and bounds the traversal (``hot_stop_names``).

Flagged inside the reachable graph (each function's own statements only;
nested defs are jit-traced device code):

- ``x.item()``
- ``jax.device_get(...)`` / bare ``device_get``
- ``x.block_until_ready()`` / ``jax.block_until_ready(x)``
- ``np.asarray(...)`` — a transfer when the argument lives on device;
  suppress with a reason when the argument is provably host data
- ``float(x[i])`` / ``int(x[i])`` — the classic device-scalar read

Name resolution is conservative (see astutil); when a finding is a
false positive because the data is host-side, the suppression reason
documents exactly that, which is the invariant made visible.
"""

from __future__ import annotations

import ast
from typing import List

from ..astutil import (calls_in, callee_name, index_functions,
                       own_statements, reachable, receiver_root)
from ..core import Finding, Pass, Project


class HostSyncPass(Pass):
    id = "host-sync-hot-path"
    summary = ("no .item()/device_get/block_until_ready/np.asarray/"
               "scalar reads in the dispatch-critical call graph")

    def run(self, project: Project) -> List[Finding]:
        cfg = project.config
        scope = [rel for rel in project.sources
                 if project.in_scope(rel, cfg.graph_scopes)]
        index = index_functions(project.sources, scope)
        roots = []
        for rel, name in cfg.hot_roots:
            roots.extend(fi for fi in index.get(name, ())
                         if fi.rel == rel)
        hot = reachable(index, roots, set(cfg.hot_stop_names))

        findings: List[Finding] = []
        for fi in hot:
            if fi.name in cfg.hot_stop_names:
                continue        # the sanctioned sync boundary itself
            for node in own_statements(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                msg = self._violation(node)
                if msg:
                    findings.append(Finding(
                        fi.rel, node.lineno, self.id,
                        f"{msg} in dispatch hot path "
                        f"({fi.qualname}, reachable from "
                        f"{'/'.join(r for _m, r in cfg.hot_roots)})"))
        return findings

    @staticmethod
    def _violation(call: ast.Call) -> str:
        f = call.func
        if isinstance(f, ast.Attribute):
            root = receiver_root(f.value)
            if f.attr == "item" and not call.args:
                return "host sync .item()"
            if f.attr == "block_until_ready":
                return "host sync block_until_ready"
            if f.attr == "device_get":
                return "host transfer device_get"
            if f.attr == "asarray" and root in ("np", "numpy"):
                return "host transfer np.asarray"
        elif isinstance(f, ast.Name):
            if f.id == "device_get":
                return "host transfer device_get"
            if (f.id in ("float", "int") and len(call.args) == 1
                    and isinstance(call.args[0], ast.Subscript)):
                return f"device-scalar read {f.id}(x[...])"
        return ""
