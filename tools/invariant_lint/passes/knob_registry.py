"""knob-registry: every TPU_* env var read must be declared once.

``runtime/knobs.py`` is the single declaration point for every ``TPU_*``
environment variable (name, type, default, subsystem, one-line doc).
This pass cross-checks three surfaces:

- **undeclared read** — code reads a ``TPU_*`` env var that knobs.py
  does not declare (the 88-read-vs-76-documented drift this PR closes);
- **stale declaration** — knobs.py declares a knob no code mentions;
- **undocumented knob** — a declared knob is absent from a docs tree's
  knob tables (docs/en AND docs/zh-CN must both list every knob);
- **stray docs knob** — the docs mention a ``TPU_*`` name that is not
  declared (e.g. a renamed or removed knob the tables kept).

Reads are detected structurally (``os.environ.get("TPU_X")``,
``os.environ["TPU_X"]``, ``os.getenv``, dict-style ``e.get`` on an env
mapping); the stale check is deliberately looser — any literal mention
in code keeps a declaration alive — so indirection like
``arm_from_env(env="TPU_FAULTS")`` doesn't false-positive.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from ..core import Finding, Pass, Project

ENV_GETTERS = {"get", "getenv", "pop", "setdefault"}


class KnobRegistryPass(Pass):
    id = "knob-registry"
    summary = ("TPU_* env reads declared in runtime/knobs.py and listed "
               "in both docs knob tables")

    def run(self, project: Project) -> List[Finding]:
        cfg = project.config
        prefix = cfg.knob_prefix
        # Lookbehind keeps substrings of longer identifiers out —
        # OLLAMA_TPU_KERNELS is not a mention of TPU_KERNELS.
        knob_re = re.compile(
            rf"(?<![A-Z0-9_]){re.escape(prefix)}[A-Z0-9_]*[A-Z0-9]")
        findings: List[Finding] = []

        declared = self._declarations(project)
        if not declared:
            src = project.source(cfg.knobs_module)
            findings.append(Finding(
                cfg.knobs_module, 1, self.id,
                "knob registry is missing or declares nothing"
                if src is None else
                "no declare(...) calls found in the knob registry"))
            declared = {}

        reads, mentions = self._scan_code(project, knob_re)

        for name, sites in sorted(reads.items()):
            if name not in declared:
                rel, line = sites[0]
                findings.append(Finding(
                    rel, line, self.id,
                    f"{name} is read here but not declared in "
                    f"{cfg.knobs_module} — declare(name, type, default, "
                    f"subsystem, doc) it first"))

        for name, line in sorted(declared.items()):
            if name not in mentions:
                findings.append(Finding(
                    cfg.knobs_module, line, self.id,
                    f"{name} is declared but no code mentions it — "
                    f"remove the stale declaration"))

        docs = self._docs_mentions(project, knob_re)
        for root, (mentioned, _sites) in docs.items():
            for name, line in sorted(declared.items()):
                if name not in mentioned:
                    findings.append(Finding(
                        cfg.knobs_module, line, self.id,
                        f"{name} is declared but missing from the "
                        f"{root} knob tables"))
        for root, (_mentioned, sites) in docs.items():
            for name, (rel, line) in sorted(sites.items()):
                if name not in declared:
                    findings.append(Finding(
                        rel, line, self.id,
                        f"docs mention {name} but {project.config.knobs_module} "
                        f"does not declare it — stale or misspelled knob"))
        return findings

    # -- declarations ---------------------------------------------------

    def _declarations(self, project: Project) -> Dict[str, int]:
        src = project.source(project.config.knobs_module)
        if src is None:
            return {}
        out: Dict[str, int] = {}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name != "declare" or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value,
                                                             str):
                out[first.value] = node.lineno
        return out

    # -- code reads & mentions ------------------------------------------

    def _scan_code(self, project: Project, knob_re) -> Tuple[
            Dict[str, List[Tuple[str, int]]], Set[str]]:
        cfg = project.config
        reads: Dict[str, List[Tuple[str, int]]] = {}
        mentions: Set[str] = set()
        for rel, src in project.sources.items():
            if rel == cfg.knobs_module:
                continue
            for m in knob_re.finditer(src.text):
                mentions.add(m.group(0))
            for node in ast.walk(src.tree):
                for name, line in self._env_reads(node, knob_re):
                    reads.setdefault(name, []).append((rel, line))
        return reads, mentions

    def _env_reads(self, node: ast.AST, knob_re):
        def literal(arg):
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and knob_re.fullmatch(arg.value)):
                return arg.value
            return None

        if isinstance(node, ast.Call):
            f = node.func
            getter = (f.attr if isinstance(f, ast.Attribute)
                      else f.id if isinstance(f, ast.Name) else None)
            if getter in ENV_GETTERS and node.args:
                name = literal(node.args[0])
                if name:
                    yield name, node.lineno
        elif isinstance(node, ast.Subscript):
            if isinstance(getattr(node, "ctx", None), ast.Load):
                name = literal(node.slice)
                if name:
                    yield name, node.lineno

    # -- docs -----------------------------------------------------------

    def _docs_mentions(self, project: Project, knob_re) -> Dict[
            str, Tuple[Set[str], Dict[str, Tuple[str, int]]]]:
        out: Dict[str, Tuple[Set[str], Dict[str, Tuple[str, int]]]] = {}
        for root in project.config.docs_roots:
            base = project.config.root / root
            mentioned: Set[str] = set()
            sites: Dict[str, Tuple[str, int]] = {}
            if base.is_dir():
                for md in sorted(base.rglob("*.md")):
                    rel = md.relative_to(project.config.root).as_posix()
                    try:
                        text = md.read_text(encoding="utf-8")
                    except UnicodeDecodeError:
                        continue
                    for i, line in enumerate(text.splitlines(), start=1):
                        for m in knob_re.finditer(line):
                            mentioned.add(m.group(0))
                            sites.setdefault(m.group(0), (rel, i))
            out[root] = (mentioned, sites)
        return out
