"""lock-order: static lock-acquisition graph, cycles and blocking calls.

The runtime holds ~24 ``threading.Lock``/``RLock`` sites across
scheduler/metrics/trace/admission/follower.  This pass:

- collects every lock *object* (``self.x = threading.Lock()`` instance
  attributes, class attributes, module-level ``X = threading.Lock()``),
  identified as ``Class.attr`` or a module-global name;
- records acquisition order: inside a ``with lockA:`` body, a nested
  ``with lockB:`` or a call into a function that (transitively) acquires
  lockB adds the edge A -> B;
- errors on cycles in that graph (the classic ABBA deadlock); a lock
  re-acquired while already held is only an error for non-reentrant
  ``Lock`` (RLock self-edges are by design);
- flags blocking calls made while holding any lock: ``time.sleep``,
  thread ``join``, untimed ``queue.get``/``Event.wait``, socket I/O,
  ``urlopen``, ``subprocess``.

Lock identity resolution: ``self.X`` binds to the enclosing class's
``Class.X`` when that class declares it, else to the unique declaring
class; ambiguous non-self receivers are skipped rather than merged —
merging distinct ``_lock`` attributes would manufacture false cycles.
Call resolution uses astutil.resolve_call (same-class ``self`` dispatch,
single-class name matches, container-method names skipped); intentional
holds (e.g. the follower control plane serialising socket sends under
its dispatch lock) carry an inline suppression explaining why the hold
is the point.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..astutil import (FUNC_NODES, FuncInfo, index_functions,
                       own_statements, receiver_root, resolve_call)
from ..core import Finding, Pass, Project

BLOCKING_SOCKET = {"sendall", "recv", "accept", "connect"}
UNTIMED_WAIT_RECV = ("queue", "_q", "ready", "event", "stop", "done")
SKIP_NODES = FUNC_NODES + (ast.ClassDef, ast.Lambda)


def _walk_calls(node: ast.AST):
    """Calls in an expression/statement, skipping nested defs+lambdas."""
    work = [node]
    while work:
        n = work.pop()
        if isinstance(n, SKIP_NODES):
            continue
        if isinstance(n, ast.Call):
            yield n
        work.extend(ast.iter_child_nodes(n))


class LockOrderPass(Pass):
    id = "lock-order"
    summary = ("no cycles in the static lock-acquisition graph; no "
               "blocking calls while holding a lock")

    def run(self, project: Project) -> List[Finding]:
        cfg = project.config
        scope = [rel for rel in project.sources
                 if project.in_scope(rel, cfg.graph_scopes)]
        index = index_functions(project.sources, scope)

        # lock registry: attr name -> {owner class or "" (module-global)}
        self.lock_owners: Dict[str, Set[str]] = {}
        self.reentrant: Set[str] = set()
        for rel in scope:
            self._collect_locks(project.sources[rel].tree)

        funcs: List[FuncInfo] = [fi for fis in index.values() for fi in fis]
        direct: Dict[int, Set[str]] = {}
        held_calls: List[Tuple[FuncInfo, str, ast.Call]] = []
        with_edges: List[Tuple[FuncInfo, str, int, str]] = []
        for fi in funcs:
            acquired: Set[str] = set()
            self._scan(fi, fi.node.body, [], acquired, held_calls,
                       with_edges)
            direct[id(fi.node)] = acquired

        # transitive lock sets (fixpoint over the name-resolved graph)
        trans = {id(fi.node): set(direct[id(fi.node)]) for fi in funcs}
        changed = True
        while changed:
            changed = False
            for fi in funcs:
                mine = trans[id(fi.node)]
                for call in self._own_calls(fi.node):
                    for target in resolve_call(call, fi.cls, index):
                        extra = trans[id(target.node)] - mine
                        if extra:
                            mine |= extra
                            changed = True

        edges: Dict[str, Set[str]] = {}
        edge_sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
        for fi, held, line, inner in with_edges:
            edges.setdefault(held, set()).add(inner)
            edge_sites.setdefault((held, inner), (fi.rel, line))
        for fi, held, call in held_calls:
            for target in resolve_call(call, fi.cls, index):
                for inner in trans[id(target.node)]:
                    edges.setdefault(held, set()).add(inner)
                    edge_sites.setdefault((held, inner),
                                          (fi.rel, call.lineno))

        findings: List[Finding] = []
        for a, b in self._cycle_edges(edges):
            rel, line = edge_sites.get((a, b), ("<unknown>", 1))
            findings.append(Finding(
                rel, line, self.id,
                f"lock-order cycle: acquiring {b} while holding {a} "
                f"participates in a cycle in the static acquisition "
                f"graph (potential deadlock)"))

        # transitive blocking ops: a held call into a function whose call
        # graph performs socket I/O / sleeps / untimed waits blocks just
        # as surely as doing it inline
        block: Dict[int, Set[str]] = {}
        for fi in funcs:
            block[id(fi.node)] = {m for c in self._own_calls(fi.node)
                                  for m in (self._blocking(c),) if m}
        changed = True
        while changed:
            changed = False
            for fi in funcs:
                mine = block[id(fi.node)]
                for call in self._own_calls(fi.node):
                    for target in resolve_call(call, fi.cls, index):
                        extra = {f"{m.split(' (via')[0]} "
                                 f"(via {target.qualname})"
                                 for m in block[id(target.node)]} - mine
                        if extra:
                            mine |= extra
                            changed = True

        for fi, held, call in held_calls:
            msg = self._blocking(call)
            if not msg:
                for target in resolve_call(call, fi.cls, index):
                    ops = block[id(target.node)]
                    if ops:
                        msg = sorted(ops)[0]
                        if " (via" not in msg:
                            msg = f"{msg} (via {target.qualname})"
                        break
            if msg:
                findings.append(Finding(
                    fi.rel, call.lineno, self.id,
                    f"{msg} while holding {held} ({fi.qualname})"))
        return findings

    # -- lock registry --------------------------------------------------

    @staticmethod
    def _lock_ctor(value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        f = value.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        return name if name in ("Lock", "RLock") else None

    def _collect_locks(self, tree: ast.AST) -> None:
        def record(attr: str, owner: str, kind: str):
            self.lock_owners.setdefault(attr, set()).add(owner)
            if kind == "RLock":
                self.reentrant.add(f"{owner}.{attr}" if owner else attr)

        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    kind = self._lock_ctor(sub.value)
                    if not kind:
                        continue
                    for t in sub.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            record(t.attr, node.name, kind)
                        elif isinstance(t, ast.Name):
                            record(t.id, node.name, kind)
        if isinstance(tree, ast.Module):
            for node in tree.body:
                if isinstance(node, ast.Assign):
                    kind = self._lock_ctor(node.value)
                    if kind:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                record(t.id, "", kind)

    def _lock_of(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            owners = self.lock_owners.get(attr)
            if not owners:
                return None
            is_self = (isinstance(expr.value, ast.Name)
                       and expr.value.id == "self")
            if is_self and cls in owners:
                return f"{cls}.{attr}"
            if len(owners) == 1:
                owner = next(iter(owners))
                return f"{owner}.{attr}" if owner else attr
            return None         # ambiguous: skip, don't merge
        if isinstance(expr, ast.Name):
            owners = self.lock_owners.get(expr.id)
            if owners and "" in owners:
                return expr.id
            if owners and len(owners) == 1:
                return f"{next(iter(owners))}.{expr.id}"
        return None

    # -- statement walk -------------------------------------------------

    def _scan(self, fi: FuncInfo, body, held: List[str],
              acquired: Set[str], held_calls, with_edges) -> None:
        for node in body:
            if isinstance(node, SKIP_NODES):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                got: List[str] = []
                for item in node.items:
                    if held:
                        for c in _walk_calls(item.context_expr):
                            held_calls.append((fi, held[-1], c))
                    lock = self._lock_of(item.context_expr, fi.cls)
                    if lock:
                        if held:
                            with_edges.append(
                                (fi, held[-1], node.lineno, lock))
                        got.append(lock)
                        acquired.add(lock)
                self._scan(fi, node.body, held + got, acquired,
                           held_calls, with_edges)
                continue
            stmt_lists, exprs = [], []
            for _field, value in ast.iter_fields(node):
                if isinstance(value, list) and value:
                    if isinstance(value[0], ast.stmt):
                        stmt_lists.append(value)
                    elif isinstance(value[0], ast.excepthandler):
                        for h in value:
                            stmt_lists.append(h.body)
                    else:
                        exprs.extend(v for v in value
                                     if isinstance(v, ast.AST))
                elif isinstance(value, ast.AST):
                    exprs.append(value)
            if held:
                for e in exprs:
                    for c in _walk_calls(e):
                        held_calls.append((fi, held[-1], c))
            for sl in stmt_lists:
                self._scan(fi, sl, held, acquired, held_calls, with_edges)

    @staticmethod
    def _own_calls(func: ast.AST):
        for node in own_statements(func):
            if isinstance(node, ast.Call):
                yield node

    # -- analysis -------------------------------------------------------

    def _cycle_edges(self,
                     edges: Dict[str, Set[str]]) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []

        def reaches(frm: str, to: str) -> bool:
            seen: Set[str] = set()
            work = [frm]
            while work:
                n = work.pop()
                if n == to:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                work.extend(edges.get(n, ()))
            return False

        for a, succs in sorted(edges.items()):
            for b in sorted(succs):
                if a == b:
                    if a not in self.reentrant:
                        out.append((a, b))
                elif reaches(b, a):
                    out.append((a, b))
        return out

    @staticmethod
    def _blocking(call: ast.Call) -> str:
        f = call.func
        kwargs = {kw.arg for kw in call.keywords}
        if isinstance(f, ast.Attribute):
            root = receiver_root(f.value)
            recv = (f.value.attr if isinstance(f.value, ast.Attribute)
                    else f.value.id if isinstance(f.value, ast.Name)
                    else "")
            if f.attr == "sleep" and root == "time":
                return "time.sleep"
            if f.attr == "join" and any(
                    k in recv.lower() for k in ("thread", "worker",
                                                "proc")):
                return "thread join"
            if (f.attr == "get" and "timeout" not in kwargs
                    and len(call.args) < 2
                    and any(k in recv.lower() for k in ("queue", "_q"))):
                return "untimed queue.get"
            if (f.attr == "wait" and not call.args
                    and "timeout" not in kwargs
                    and any(k in recv.lower() for k in UNTIMED_WAIT_RECV)):
                return "untimed .wait()"
            if f.attr in BLOCKING_SOCKET and isinstance(f.value,
                                                        (ast.Name,
                                                         ast.Attribute)):
                return f"socket {f.attr}"
            if f.attr == "urlopen":
                return "urllib urlopen"
            if root == "subprocess":
                return f"subprocess.{f.attr}"
        elif isinstance(f, ast.Name):
            if f.id in ("urlopen", "create_connection"):
                return f.id
        return ""
