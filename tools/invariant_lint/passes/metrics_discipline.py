"""metrics-discipline: every metric family described and pre-seeded.

The runtime metrics-lint CI job validates a live scrape — but it can
only see label combos that happened to fire.  This pass closes the gap
statically: every ``tpu_model_*`` family constructed anywhere via
``.inc`` / ``.observe`` / ``.gauge_fn`` must be

- **described** — a ``describe(name, help)`` call exists (HELP/TYPE on
  every series is the scrape contract), and
- for counters, **pre-seeded** — ``server/metrics.py`` must seed the
  family at 0 with the *same label-key set* the increment uses, so an
  idle scrape reads 0, not absent (the label-combo matrices: a
  ``{class=,cause=}`` increment needs ``{class=,cause=}`` seeds).

Label keys are extracted from the static text of label strings —
f-string *values* may be dynamic (tenant names), the *keys* never are.
Two seed idioms are recognised: a literal ``inc(name, 0.0, ...)`` and
the batch loop ``for n in (names...): X.inc(n, 0.0)``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..astutil import fstring_static_text
from ..core import Finding, Pass, Project

METRIC_METHODS = {"inc", "observe", "gauge_fn", "describe"}
LABEL_KEY_RE = re.compile(r'(\w+)=')


def _label_keys(node: Optional[ast.AST]) -> Optional[FrozenSet[str]]:
    """Static label-key set of a label argument; None = dynamic."""
    if node is None:
        return frozenset()
    text = fstring_static_text(node)
    if text is None:
        return None
    return frozenset(LABEL_KEY_RE.findall(text))


def _metric_calls(tree: ast.AST, prefix: str):
    """(method, name, name_is_literal, call) for metric-registry calls."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute) or f.attr not in METRIC_METHODS:
            continue
        if not node.args:
            continue
        first = node.args[0]
        if (isinstance(first, ast.Constant) and isinstance(first.value, str)
                and first.value.startswith(prefix)):
            yield f.attr, first.value, True, node
        elif isinstance(first, ast.Name):
            yield f.attr, first.id, False, node


class MetricsDisciplinePass(Pass):
    id = "metrics-discipline"
    summary = ("metric families described + counters pre-seeded with "
               "matching label-key combos")

    def run(self, project: Project) -> List[Finding]:
        cfg = project.config
        prefix = cfg.metric_prefix
        described: Set[str] = set()
        # family -> set of seeded label-key sets
        seeded: Dict[str, Set[FrozenSet[str]]] = {}

        metrics_src = project.source(cfg.metrics_module)
        if metrics_src is not None:
            self._collect_registry(metrics_src.tree, prefix, described,
                                   seeded)
        # describe() calls elsewhere also count as descriptions
        for rel, src in project.sources.items():
            if rel == cfg.metrics_module:
                continue
            for method, name, lit, _node in _metric_calls(src.tree, prefix):
                if method == "describe" and lit:
                    described.add(name)

        findings: List[Finding] = []
        for rel, src in project.sources.items():
            for method, name, lit, node in _metric_calls(src.tree, prefix):
                if not lit or method == "describe":
                    continue
                if name not in described:
                    findings.append(Finding(
                        rel, node.lineno, self.id,
                        f"metric family {name} is used but never "
                        f"described — add describe() in "
                        f"{cfg.metrics_module}"))
                if method != "inc" or rel == cfg.metrics_module:
                    continue
                keys = _label_keys(node.args[2] if len(node.args) > 2
                                   else self._kw(node, "labels"))
                combos = seeded.get(name)
                if not combos:
                    findings.append(Finding(
                        rel, node.lineno, self.id,
                        f"counter {name} is incremented but never "
                        f"pre-seeded at 0 in {cfg.metrics_module} — an "
                        f"idle scrape must read 0, not absent"))
                elif keys is not None and keys not in combos:
                    shown = ",".join(sorted(keys)) or "<none>"
                    findings.append(Finding(
                        rel, node.lineno, self.id,
                        f"counter {name} incremented with label keys "
                        f"{{{shown}}} but no pre-seed uses that key set "
                        f"— seed the full combo matrix in "
                        f"{cfg.metrics_module}"))
        return findings

    @staticmethod
    def _kw(call: ast.Call, name: str) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _collect_registry(self, tree: ast.AST, prefix: str,
                          described: Set[str],
                          seeded: Dict[str, Set[FrozenSet[str]]]) -> None:
        zero = (0, 0.0)
        for method, name, lit, node in _metric_calls(tree, prefix):
            if not lit:
                continue
            if method == "describe":
                described.add(name)
            elif method == "inc" and len(node.args) > 1:
                v = node.args[1]
                if isinstance(v, ast.Constant) and v.value in zero:
                    keys = _label_keys(
                        node.args[2] if len(node.args) > 2
                        else self._kw(node, "labels"))
                    seeded.setdefault(name, set()).add(
                        keys if keys is not None else frozenset())
        # batch idiom: for _n in ("a", "b", ...): X.inc(_n, 0.0)
        for loop in ast.walk(tree):
            if not isinstance(loop, ast.For):
                continue
            if not isinstance(loop.target, ast.Name):
                continue
            if not isinstance(loop.iter, (ast.Tuple, ast.List)):
                continue
            names = [e.value for e in loop.iter.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)
                     and e.value.startswith(prefix)]
            if not names:
                continue
            for node in ast.walk(loop):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "inc" and node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id == loop.target.id):
                    keys = _label_keys(
                        node.args[2] if len(node.args) > 2
                        else self._kw(node, "labels")) or frozenset()
                    for n in names:
                        seeded.setdefault(n, set()).add(keys)
